"""Streaming UniRef90 XML → SQLite ETL (reference C1, redesigned).

The reference streams `uniref90.xml.gz` with lxml iterparse + xpath and
buffers 100k-record pandas chunks to `to_sql` (reference
uniref_dataset.py:25-155). This version:

- uses stdlib `xml.etree.ElementTree.iterparse` with aggressive subtree
  release (same memory profile, no lxml requirement);
- processes entries with plain dicts and writes chunks via one
  `executemany` per chunk — no DataFrame construction per 100k rows;
- ACTUALLY stores ancestor-completed GO indices (the reference computes
  the completion and then indexes the raw list — reference
  uniref_dataset.py:124-126, SURVEY ledger #6);
- supports task-array sharding (`shard_index`/`num_shards`): shard k
  processes entries where `entry_number % num_shards == k`, each writing
  its own SQLite file — the embarrassing CPU parallelism the reference
  provides via SLURM helpers (reference shared_utils/util.py:1121-1157,
  SURVEY C17), decoupled here from any particular scheduler.

Schema (table `protein_annotations`) keeps the reference's column names so
downstream joins are drop-in (reference uniref_dataset.py:101-119):
  entry_index INTEGER, tax_id, uniprot_name TEXT,
  go_annotations TEXT(json: category → [ids]),
  flat_go_annotations TEXT(json: sorted raw ids),
  n_go_annotations INTEGER,
  complete_go_annotation_indices TEXT(json: sorted completed indices),
  n_complete_go_annotations INTEGER.
"""

from __future__ import annotations

import gzip
import json
import sqlite3
from collections import Counter
from typing import Dict, List, Optional
from xml.etree import ElementTree

from proteinbert_tpu.etl.go_ontology import GoOntology
from proteinbert_tpu.utils.logging import log

_NS = "{http://uniprot.org/uniref}"

# reference uniref_dataset.py:151-155
GO_ANNOTATION_CATEGORIES = (
    "GO Molecular Function",
    "GO Biological Process",
    "GO Cellular Component",
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS protein_annotations (
    entry_index INTEGER PRIMARY KEY,
    tax_id INTEGER,
    uniprot_name TEXT NOT NULL,
    go_annotations TEXT NOT NULL,
    flat_go_annotations TEXT NOT NULL,
    n_go_annotations INTEGER NOT NULL,
    complete_go_annotation_indices TEXT NOT NULL,
    n_complete_go_annotations INTEGER NOT NULL
)
"""

# Per-shard aggregates persisted next to the rows so a task-array run can
# be merged losslessly (the reference keeps these only in memory,
# reference uniref_dataset.py:43-45, which would silently produce
# per-shard-only counts in any sharded run).
_AGG_SCHEMA = """
CREATE TABLE IF NOT EXISTS go_record_counts (
    go_id TEXT PRIMARY KEY,
    count INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS etl_stats (
    key TEXT PRIMARY KEY,
    value INTEGER NOT NULL
)
"""

_INSERT = """
INSERT OR REPLACE INTO protein_annotations VALUES (?,?,?,?,?,?,?,?)
"""


class UnirefToSqliteParser:
    """One pass over the UniRef XML; see module docstring for the deltas
    vs the reference class of the same name."""

    def __init__(
        self,
        uniref_xml_path: str,
        ontology: GoOntology,
        sqlite_path: str,
        verbose: bool = True,
        log_progress_every: int = 100_000,
        chunk_size: int = 100_000,
        shard_index: int = 0,
        num_shards: int = 1,
        max_entries: Optional[int] = None,
    ):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard {shard_index} outside [0, {num_shards})")
        self.xml_path = uniref_xml_path
        self.ontology = ontology
        self.sqlite_path = sqlite_path
        self.verbose = verbose
        self.log_progress_every = log_progress_every
        self.chunk_size = chunk_size
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.max_entries = max_entries

        # Aggregates (reference uniref_dataset.py:43-45).
        self.go_record_counts: Counter = Counter()   # go_id → #records (completed)
        self.unrecognized_go: Counter = Counter()
        # Hostile-input accounting: UniRef dumps in the wild contain
        # malformed entries (missing representativeMember/dbReference/
        # taxonomy) and occasionally arrive truncated (an interrupted
        # download cuts the gzip stream mid-member). A corpus-scale ETL
        # must COUNT and continue, never crash hours in — the reference
        # crashes on the first malformed entry (AttributeError off
        # find()) and on any truncated archive.
        self.skipped_entries: Counter = Counter()    # reason → count
        self.unrecognized_go_categories: Counter = Counter()
        self.stream_error: Optional[str] = None      # truncation/parse fault
        self.n_records_with_any_go = 0
        self.n_entries = 0

    def parse(self) -> None:
        conn = sqlite3.connect(self.sqlite_path)
        conn.execute(_SCHEMA)
        conn.executescript(_AGG_SCHEMA)
        buf: List[tuple] = []
        try:
            entries = self._iter_entries()
            while True:
                try:
                    i, entry = next(entries)
                except StopIteration:
                    break
                except (EOFError, OSError, ElementTree.ParseError) as e:
                    # Truncated gzip member (EOFError), corrupt archive
                    # (BadGzipFile is an OSError), or XML cut mid-entry
                    # (ParseError): keep every row parsed so far, record
                    # the fault loudly, and finish cleanly — the partial
                    # DB plus the fault stat is recoverable state, a
                    # traceback after hours of streaming is not.
                    self.stream_error = f"{type(e).__name__}: {e}"
                    log(f"uniref parse: input stream ended abnormally "
                        f"after {self.n_entries} entries ({self.stream_error}"
                        "); keeping rows parsed so far")
                    break
                if self.verbose and i and i % self.log_progress_every == 0:
                    log(f"uniref parse: {i} entries")
                if i % self.num_shards != self.shard_index:
                    continue
                row = self._process_entry(i, entry)
                if row is None:
                    continue
                buf.append(row)
                if len(buf) >= self.chunk_size:
                    self._flush(conn, buf)
                    buf = []
            if buf:
                self._flush(conn, buf)
            self._save_aggregates(conn)
        finally:
            conn.close()
        if self.verbose:
            if self.unrecognized_go:
                log(f"ignored unrecognized GO ids: "
                    f"{dict(self.unrecognized_go.most_common(20))} "
                    f"({len(self.unrecognized_go)} distinct)")
            if self.unrecognized_go_categories:
                log(f"ignored unknown GO categories: "
                    f"{dict(self.unrecognized_go_categories)}")
            if self.skipped_entries:
                log(f"skipped malformed entries: "
                    f"{dict(self.skipped_entries)}")
            log(f"parsed {self.n_entries} entries in shard "
                f"{self.shard_index}/{self.num_shards}; "
                f"{self.n_records_with_any_go} with any completed GO annotation")

    def _iter_entries(self):
        """Stream top-level <entry> elements, releasing each after use.

        ElementTree's iterparse keeps the whole tree unless cleared; the
        root-clear below is the stdlib equivalent of the reference's
        lxml fast-iter recipe (reference uniref_dataset.py:374-393).
        """
        opener = gzip.open if self.xml_path.endswith(".gz") else open
        with opener(self.xml_path, "rb") as f:
            context = ElementTree.iterparse(f, events=("start", "end"))
            _, root = next(context)  # grab the document root
            i = 0
            for event, elem in context:
                if event == "end" and elem.tag == _NS + "entry":
                    yield i, elem
                    i += 1
                    root.clear()  # free the finished entry subtree
                    if self.max_entries is not None and i >= self.max_entries:
                        break

    def _process_entry(self, i: int, entry) -> Optional[tuple]:
        """One <entry> → row tuple, or None (counted in skipped_entries)
        for entries missing the pieces the schema cannot do without."""
        self.n_entries += 1
        repr_member = entry.find(_NS + "representativeMember")
        if repr_member is None:
            self.skipped_entries["no_representative_member"] += 1
            return None
        db_ref = repr_member.find(_NS + "dbReference")
        if db_ref is None:
            self.skipped_entries["no_db_reference"] += 1
            return None
        uniprot_name = db_ref.get("id")
        if not uniprot_name:
            self.skipped_entries["no_uniprot_id"] += 1
            return None

        tax_id = None
        go: Dict[str, List[str]] = {c: [] for c in GO_ANNOTATION_CATEGORIES}
        for prop in db_ref.iter(_NS + "property"):
            ptype = prop.get("type")
            if ptype == "NCBI taxonomy":
                try:
                    tax_id = int(prop.get("value"))
                except (TypeError, ValueError):
                    tax_id = None
            elif ptype in go:
                value = prop.get("value")
                if value:
                    go[ptype].append(value)
            elif ptype and ptype.startswith("GO "):
                # A GO-looking category this schema doesn't know (a new
                # UniProt export aspect, or a typo'd dump): counted, not
                # silently folded into the known three and not a crash.
                self.unrecognized_go_categories[ptype] += 1
        if tax_id is None:
            self.skipped_entries["no_tax_id"] += 1
            return None
        go = {c: sorted(set(v)) for c, v in go.items()}

        flat = sorted(set().union(*go.values()))
        for gid in flat:
            if gid not in self.ontology.ancestors:
                self.unrecognized_go[gid] += 1
        complete_ids = self.ontology.complete(flat)
        complete_indices = sorted(self.ontology.id_to_index[g] for g in complete_ids)
        if complete_indices:
            self.n_records_with_any_go += 1
            self.go_record_counts.update(complete_ids)

        return (
            i, tax_id, uniprot_name,
            json.dumps(go), json.dumps(flat), len(flat),
            json.dumps(complete_indices), len(complete_indices),
        )

    def _flush(self, conn: sqlite3.Connection, buf: List[tuple]) -> None:
        with conn:
            conn.executemany(_INSERT, buf)

    def _save_aggregates(self, conn: sqlite3.Connection) -> None:
        with conn:
            conn.executemany(
                "INSERT OR REPLACE INTO go_record_counts VALUES (?,?)",
                list(self.go_record_counts.items()),
            )
            stats = [("n_records_with_any_go", self.n_records_with_any_go),
                     ("n_entries", self.n_entries)]
            stats += [(f"skipped_{reason}", count)
                      for reason, count in self.skipped_entries.items()]
            stats += [("n_stream_errors", 1 if self.stream_error else 0)]
            conn.executemany(
                "INSERT OR REPLACE INTO etl_stats VALUES (?,?)", stats)


def read_aggregates(sqlite_path: str):
    """(go_record_counts: Counter, n_records_with_any_go: int) persisted
    by parse() — from a single-shard or merged DB."""
    conn = sqlite3.connect(sqlite_path)
    try:
        counts = Counter(dict(conn.execute(
            "SELECT go_id, count FROM go_record_counts")))
        row = conn.execute(
            "SELECT value FROM etl_stats WHERE key='n_records_with_any_go'"
        ).fetchone()
    finally:
        conn.close()
    return counts, (row[0] if row else 0)


def merge_shard_dbs(shard_paths: List[str], out_path: str) -> int:
    """Concatenate per-shard SQLite files (from a task-array run) into
    one DB, SUMMING the persisted per-shard aggregates; returns total
    rows. Entry indices are disjoint by construction (shard k owns
    i % N == k)."""
    out = sqlite3.connect(out_path)
    out.execute(_SCHEMA)
    out.executescript(_AGG_SCHEMA)
    total = 0
    with out:
        for p in shard_paths:
            out.execute("ATTACH DATABASE ? AS shard", (p,))
            out.execute(
                "INSERT OR REPLACE INTO protein_annotations "
                "SELECT * FROM shard.protein_annotations"
            )
            out.execute(
                "INSERT INTO go_record_counts "
                "SELECT go_id, count FROM shard.go_record_counts WHERE true "
                "ON CONFLICT(go_id) DO UPDATE SET "
                "count = count + excluded.count"
            )
            out.execute(
                "INSERT INTO etl_stats "
                "SELECT key, value FROM shard.etl_stats WHERE true "
                "ON CONFLICT(key) DO UPDATE SET value = value + excluded.value"
            )
            total += out.execute(
                "SELECT COUNT(*) FROM shard.protein_annotations"
            ).fetchone()[0]
            out.commit()
            out.execute("DETACH DATABASE shard")
    out.close()
    return total
