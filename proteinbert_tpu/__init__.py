"""proteinbert_tpu — a TPU-native (JAX/XLA/Pallas/pjit) ProteinBERT framework.

A ground-up, TPU-first re-design with the full capability surface of the
reference repo Aedelon/ProteinBERT-PyTorch-Replication (surveyed in
/root/repo/SURVEY.md): offline UniRef90+GO ETL, online denoising corruption
pipeline, the dual-track (local sequence / global annotation) ProteinBERT
model, pretraining and fine-tuning engines, checkpoint/resume, and — new in
this build, absent in the reference — a distributed layer (data/tensor/
sequence parallelism over a `jax.sharding.Mesh`), Pallas fused kernels, and a
real test suite.

Package map (≈ reference layer map, SURVEY.md §1):
  configs/   dataclass config system (reference had none — SURVEY §5 "Config")
  data/      online pipeline: vocab, tokenization, corruption, datasets
             (reference ProteinBERT/data_processing.py)
  etl/       offline UniRef90 XML → SQLite → HDF5 pipeline
             (reference ProteinBERT/uniref_dataset.py)
  models/    dual-track model (reference ProteinBERT/modules.py)
  ops/       losses, metrics, conv helpers
  kernels/   Pallas TPU kernels (hot-path fused local-track block)
  parallel/  mesh, sharding rules, sequence parallelism (reference: absent)
  train/     pretrain/fine-tune engines, schedules, checkpointing
             (reference ProteinBERT/utils.py)
  serve/     online inference: continuous micro-batching over length
             buckets, result cache, HTTP endpoint (reference: absent)
  utils/     logging/profiling/task-array utilities
             (reference ProteinBERT/shared_utils/util.py)
  cli/       entry points (reference create_uniref_db.py etc.)
"""

__version__ = "0.1.0"

import jax as _jax

if not _jax.config.jax_threefry_partitionable:
    # The framework's core contracts — on-device corruption whose stream
    # is identical sharded and unsharded (sharded train_step ==
    # single-device train_step, tests/test_parallel.py), byte-identical
    # checkpoint resume across mesh shapes — require the partitionable
    # threefry lowering. jax >= 0.5 defaults it on; jax 0.4.x defaults
    # it OFF, which both changes the random stream and breaks
    # sharded-vs-single-device parity. Pin the new-jax default at
    # package import, before any RNG use, so the stream is one thing
    # everywhere. (Not inside make_mesh: flipping the flag mid-process
    # would split the stream between pre- and post-mesh phases.)
    _jax.config.update("jax_threefry_partitionable", True)
