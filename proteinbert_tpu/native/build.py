"""Compile-on-first-use infrastructure for the native components.

g++ is in the base image; pybind11 is not, so the ABI is plain extern-"C"
functions over ctypes. Shared objects are cached in `_build/` next to the
sources, keyed by a hash of the source text and compile flags — editing a
.cpp transparently rebuilds, and concurrent builders (pytest-xdist, SLURM
task arrays) race benignly via an atomic rename.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_CXX = os.environ.get("CXX", "g++")
_FLAGS = ["-O3", "-shared", "-fPIC", "-std=c++17"]

_cache: dict = {}


def _so_path(name: str, src: str) -> str:
    digest = hashlib.sha256(
        (src + " ".join(_FLAGS) + _CXX).encode()
    ).hexdigest()[:16]
    return os.path.join(_BUILD_DIR, f"{name}-{digest}.so")


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """CDLL for `<name>.cpp` in this directory, building if needed;
    None (once, logged) when the toolchain is unavailable or the build
    fails — callers then use their Python fallback."""
    if name in _cache:
        return _cache[name]
    lib = None
    try:
        src_path = os.path.join(_SRC_DIR, f"{name}.cpp")
        with open(src_path) as f:
            src = f.read()
        so = _so_path(name, src)
        if not os.path.exists(so):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
            os.close(fd)
            try:
                subprocess.run(
                    [_CXX, *_FLAGS, src_path, "-o", tmp],
                    check=True, capture_output=True, text=True, timeout=120,
                )
                os.chmod(tmp, 0o644)  # mkstemp is 0600: unreadable on
                # shared checkouts, silently demoting other users to the
                # numpy fallback
                os.replace(tmp, so)  # atomic: racing builders both win
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            logger.info("built native %s -> %s", name, so)
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning(
            "native %s unavailable (%s); using Python fallback", name, e)
    _cache[name] = lib
    return lib


def native_available(name: str = "tokenizer") -> bool:
    return load_library(name) is not None
