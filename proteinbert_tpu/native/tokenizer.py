"""ctypes wrapper for the C++ batch tokenizer (tokenizer.cpp)."""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from proteinbert_tpu.data.vocab import get_vocab
from proteinbert_tpu.native.build import load_library

_configured = False


_ABI_VERSION = 2  # must match pbt_abi_version() and the argtypes below


def _lib():
    global _configured
    lib = load_library("tokenizer")
    if lib is not None and not _configured:
        lib.pbt_abi_version.restype = ctypes.c_int32  # explicit, not c_int
        got = lib.pbt_abi_version()
        if got != _ABI_VERSION:
            # Loud and permanent: stale argtypes against a changed C
            # signature would corrupt memory, not degrade gracefully.
            raise RuntimeError(
                f"native tokenizer ABI {got} != expected {_ABI_VERSION}; "
                "update tokenizer.py's argtypes and _ABI_VERSION together")
        lib.pbt_tokenize_batch.restype = None
        lib.pbt_tokenize_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        _configured = True
    return lib


def tokenize_batch_native(
    seqs: Sequence[str],
    seq_len: int,
    crop_seed: Optional[int] = None,
    row_ids: Optional[np.ndarray] = None,
) -> Optional[np.ndarray]:
    """(B, seq_len) int32 batch via the C++ kernel, or None when the
    native library is unavailable (callers fall back to the numpy path).

    Matches transforms.tokenize_batch BIT-FOR-BIT: long rows take the
    counter-based window splitmix64(crop_seed + row_id) when `crop_seed`
    is given (transforms.crop_starts computes the same formula in numpy),
    else head-truncated.
    """
    lib = _lib()
    if lib is None:
        return None
    joined = "".join(seqs).encode("ascii", errors="replace")
    offsets = np.zeros(len(seqs) + 1, dtype=np.int64)
    np.cumsum([len(s) for s in seqs], out=offsets[1:])
    out = np.empty((len(seqs), seq_len), dtype=np.int32)
    buf = np.frombuffer(joined, dtype=np.uint8) if joined else np.zeros(1, np.uint8)
    lut = get_vocab()._lut
    if row_ids is None:
        row_ids = np.arange(len(seqs), dtype=np.int64)
    else:
        row_ids = np.ascontiguousarray(row_ids, dtype=np.int64)
    lib.pbt_tokenize_batch(
        buf.ctypes.data, offsets.ctypes.data,
        len(seqs), seq_len, lut.ctypes.data,
        (crop_seed or 0) & 0xFFFFFFFFFFFFFFFF,
        1 if crop_seed is not None else 0,
        row_ids.ctypes.data,
        out.ctypes.data,
    )
    return out
