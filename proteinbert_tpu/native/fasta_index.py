"""ctypes wrapper for the C++ .fai builder (fasta_index.cpp)."""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from proteinbert_tpu.native.build import load_library

_configured = False

_ABI_VERSION = 2  # must match pbt_fai_abi_version() and the argtypes below

_ERR_IO = -1
_ERR_NON_UNIFORM = -2
_NAME_CAP = 4096


def _lib():
    global _configured
    lib = load_library("fasta_index")
    if lib is not None and not _configured:
        got = lib.pbt_fai_abi_version()
        if got != _ABI_VERSION:
            raise RuntimeError(
                f"native fasta_index ABI {got} != expected {_ABI_VERSION}; "
                "update fasta_index.py's argtypes and _ABI_VERSION together")
        lib.pbt_build_fai.restype = ctypes.c_int64
        lib.pbt_build_fai.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.c_int64,
        ]
        _configured = True
    return lib


def build_fai_native(fasta_path: str, fai_path: str) -> Optional[int]:
    """Write the .fai via the C++ scanner; returns the record count, or
    None when the native library is unavailable (callers fall back to the
    Python loop in etl/fasta.build_index).

    Raises ValueError on ragged (non-uniformly wrapped) records — the
    same condition AND message shape as the Python path (record name, or
    None for ragged data before the first header).
    """
    lib = _lib()
    if lib is None:
        return None
    had_header = ctypes.c_int32(0)
    err_name = ctypes.create_string_buffer(_NAME_CAP)
    rc = lib.pbt_build_fai(
        os.fsencode(fasta_path), os.fsencode(fai_path),
        ctypes.byref(had_header), err_name, _NAME_CAP)
    if rc == _ERR_NON_UNIFORM:
        name = err_name.value.decode(errors="replace") \
            if had_header.value else None
        raise ValueError(
            f"record {name!r} in {fasta_path} has non-uniform "
            "line widths; re-wrap the FASTA before indexing")
    if rc == _ERR_IO:
        raise OSError(f"native .fai build failed for {fasta_path}")
    return int(rc)
