// Native host-side batch tokenizer (crop → LUT encode → sos/eos → pad).
//
// The per-batch host work feeding the TPU is Python/numpy per-row
// tokenization (proteinbert_tpu/data/transforms.py, mirroring reference
// ProteinBERT/data_processing.py:159-180 which runs it in DataLoader
// workers). TPU hosts give the input pipeline few, weak cores, so the
// inner loop is done here in C++: one call tokenizes a whole batch from a
// concatenated byte buffer with zero per-row Python overhead.
//
// Contract (mirrors transforms.tokenize): row i holds
//   [SOS=1, lut[s[0]], ..., lut[s[len-1]], EOS=2, PAD=0...]
// with sequences longer than seq_len-2 cropped to a COUNTER-BASED window
// when do_crop — start = splitmix64(seed + row_ids[i]) % span, the same
// formula transforms.crop_starts computes in numpy, so the two paths
// produce bit-identical batches and a row's window depends only on
// (seed, global row id), never on batch composition or RNG state (the
// byte-deterministic-resume scheme, VERDICT r1 Weak #3) — else
// head-truncated.
//
// The 256-entry LUT is passed in from Python (data/vocab.py stays the
// single source of truth for the id space).

#include <cstdint>

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

extern "C" {

void pbt_tokenize_batch(const uint8_t* bytes, const int64_t* offsets,
                        int64_t n, int64_t seq_len, const int32_t* lut,
                        uint64_t seed, int32_t do_crop,
                        const int64_t* row_ids, int32_t* out) {
  const int64_t cap = seq_len - 2;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* s = bytes + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t start = 0;
    if (len > cap) {
      if (do_crop) {
        uint64_t r = splitmix64(seed + static_cast<uint64_t>(row_ids[i]));
        start = static_cast<int64_t>(r % static_cast<uint64_t>(len - cap + 1));
      }
      len = cap;
    }
    int32_t* row = out + i * seq_len;
    row[0] = 1;  // <sos>
    int64_t j = 0;
    for (; j < len; ++j) row[1 + j] = lut[s[start + j]];
    row[1 + len] = 2;  // <eos>
    for (j = len + 2; j < seq_len; ++j) row[j] = 0;  // <pad>
  }
}

int32_t pbt_abi_version(void) { return 2; }

}  // extern "C"
