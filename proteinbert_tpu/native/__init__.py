"""Native (C++) host runtime components, compiled on first use.

The reference has no native code at all (SURVEY §2 native-code census);
this package supplies the TPU build's host-side native pieces. Components
are built from the sources in this directory with the system toolchain on
first import, cached by source hash under `_build/`, and loaded via
ctypes — if no compiler is available everything falls back to the numpy
implementations transparently (`native_available()` reports which path is
live).
"""

from proteinbert_tpu.native.build import load_library, native_available
from proteinbert_tpu.native.fasta_index import build_fai_native
from proteinbert_tpu.native.tokenizer import tokenize_batch_native

__all__ = ["build_fai_native", "load_library", "native_available",
           "tokenize_batch_native"]
