// Native samtools-style .fai builder (the host-side hot loop of the ETL's
// sequence-join stage: the reference indexes UniRef90's ~60 GB FASTA
// through pyfaidx, reference uniref_dataset.py:274-320; here the index
// format is built directly — etl/fasta.py holds the Python fallback this
// must match byte-for-byte, including its non-uniform-line-width error).
//
// ABI: plain extern "C" over ctypes (see native/build.py — pybind11 is
// not in the image). Parity-tested in tests/test_native.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

constexpr int32_t kAbiVersion = 2;

// Return codes for pbt_build_fai.
constexpr int64_t kErrIo = -1;          // open/read/write failure
constexpr int64_t kErrNonUniform = -2;  // ragged line widths inside a record

struct Record {
  std::string name;
  int64_t rlen = 0;
  int64_t seq_offset = 0;
  int64_t line_bases = 0;
  int64_t line_bytes = 0;
};

bool flush(const Record& r, FILE* out) {
  return std::fprintf(out, "%s\t%lld\t%lld\t%lld\t%lld\n", r.name.c_str(),
                      (long long)r.rlen, (long long)r.seq_offset,
                      (long long)r.line_bases, (long long)r.line_bytes) >= 0;
}

bool is_space(char c) {
  // Python str.split() whitespace (the fallback parses names with it).
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

}  // namespace

extern "C" {

int32_t pbt_fai_abi_version() { return kAbiVersion; }

// Scan `fasta_path`, write the index to `fai_path`. Returns the record
// count (>= 0) or a kErr* code. On kErrNonUniform: *had_header reports
// whether any '>' header preceded the error (0 mirrors the Python
// fallback's `record None`), and the offending record's name is copied
// into err_name (NUL-terminated, truncated to err_name_cap).
int64_t pbt_build_fai(const char* fasta_path, const char* fai_path,
                      int32_t* had_header, char* err_name,
                      int64_t err_name_cap) {
  FILE* in = std::fopen(fasta_path, "rb");
  if (!in) return kErrIo;
  FILE* out = std::fopen(fai_path, "wb");
  if (!out) {
    std::fclose(in);
    return kErrIo;
  }
  // Large stdio buffers: the loop is getline-bound.
  static thread_local char inbuf[1 << 22];
  static thread_local char outbuf[1 << 20];
  std::setvbuf(in, inbuf, _IOFBF, sizeof(inbuf));
  std::setvbuf(out, outbuf, _IOFBF, sizeof(outbuf));

  char* line = nullptr;
  size_t cap = 0;
  int64_t offset = 0;
  int64_t n_records = 0;
  bool in_record = false;  // a '>' header has been seen
  bool short_line_seen = false;
  Record rec;
  int64_t result = kErrIo;

  ssize_t got;
  while ((got = ::getline(&line, &cap, in)) != -1) {
    if (line[0] == '>') {
      if (in_record) {
        if (!flush(rec, out)) goto done;
        ++n_records;
      }
      // name = first whitespace-delimited word after '>' (leading
      // whitespace skipped, like the fallback's raw[1:].split()).
      int64_t start = 1;
      while (start < got && is_space(line[start])) ++start;
      int64_t end = start;
      while (end < got && !is_space(line[end])) ++end;
      rec = Record{};
      rec.name.assign(line + start, end - start);
      rec.seq_offset = offset + got;
      in_record = true;
      short_line_seen = false;
    } else {
      // Sequence data is validated even before the first header (the
      // Python fallback does — such lines feed its width checks but are
      // never flushed, since flushing requires a header).
      int64_t stripped = got;
      while (stripped > 0 &&
             (line[stripped - 1] == '\n' || line[stripped - 1] == '\r'))
        --stripped;
      if (stripped > 0) {
        // Offset arithmetic in FastaReader.fetch() only holds for
        // uniformly wrapped records (all lines equal width except
        // possibly the last) — reject ragged input, like the Python path.
        if (short_line_seen ||
            (rec.line_bases && stripped > rec.line_bases)) {
          if (had_header) *had_header = in_record ? 1 : 0;
          if (err_name && err_name_cap > 0) {
            int64_t n = (int64_t)rec.name.size();
            if (n > err_name_cap - 1) n = err_name_cap - 1;
            std::memcpy(err_name, rec.name.data(), n);
            err_name[n] = '\0';
          }
          result = kErrNonUniform;
          goto done;
        }
        if (rec.line_bases == 0) {
          rec.line_bases = stripped;
          rec.line_bytes = got;
        } else if (stripped < rec.line_bases) {
          short_line_seen = true;
        }
        rec.rlen += stripped;
      } else if (rec.line_bases) {
        // Blank line inside a record: legal only if nothing follows.
        short_line_seen = true;
      }
    }
    offset += got;
  }
  if (std::ferror(in)) goto done;
  if (in_record) {
    if (!flush(rec, out)) goto done;
    ++n_records;
  }
  result = n_records;

done:
  std::free(line);
  std::fclose(in);
  if (std::fclose(out) != 0 && result >= 0) result = kErrIo;
  return result;
}

}  // extern "C"
