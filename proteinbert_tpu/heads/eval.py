"""Downstream-task eval harness (ISSUE 8): score registered heads.

Finetune QUALITY must gate like perf does: every eval produces a
schema-versioned `head_eval` event (obs/events.py) on the shared
telemetry stream, and `bench.py --heads` mirrors the aggregate score
onto `bench_events.jsonl` where the trajectory sentinel
(tools/bench_trajectory.py) fits noise bands over history — a silent
finetune regression then surfaces exactly like a throughput regression.

Per-task metrics (the ProteinBERT paper's benchmark shapes):

  token_classification     per-residue accuracy over labeled positions
                           + a multilabel AUC proxy (mean one-vs-rest
                           rank-AUC over classes);
  sequence_classification  accuracy + the same AUC proxy;
  sequence_regression      Spearman rank correlation + MSE.

The AUC proxy is the exact Mann-Whitney rank statistic per class
(ties mid-ranked), averaged over classes that have both positives and
negatives — "proxy" because classes the split never exercises are
skipped rather than imputed. Every metric dict also carries a
normalized `score` (accuracy for classification, Spearman for
regression) so heterogeneous-task registries aggregate on one scale.

Forward passes run through the SAME split-apply executables serving
uses (heads/apply.py), so an eval score describes the numbers the
server actually returns.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.data.vocab import PAD_ID
from proteinbert_tpu.heads import apply as heads_apply


def _ranks(x: np.ndarray) -> np.ndarray:
    """Average ranks (1-based, ties mid-ranked) — the shared primitive
    under both Spearman and the rank-AUC."""
    order = np.argsort(x, kind="mergesort")
    ranks = np.empty(len(x), np.float64)
    ranks[order] = np.arange(1, len(x) + 1, dtype=np.float64)
    # Average the ranks inside each tie group.
    sx = x[order]
    i = 0
    while i < len(sx):
        j = i
        while j + 1 < len(sx) and sx[j + 1] == sx[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    return ranks


def spearman(pred: np.ndarray, target: np.ndarray) -> float:
    """Spearman rank correlation (Pearson over average ranks); 0.0 for
    degenerate (constant) inputs rather than NaN."""
    pred = np.asarray(pred, np.float64).ravel()
    target = np.asarray(target, np.float64).ravel()
    if len(pred) < 2:
        return 0.0
    rp, rt = _ranks(pred), _ranks(target)
    sp, st = rp.std(), rt.std()
    if sp == 0.0 or st == 0.0:
        return 0.0
    return float(((rp - rp.mean()) * (rt - rt.mean())).mean() / (sp * st))


def auc_proxy(scores: np.ndarray, labels: np.ndarray) -> Optional[float]:
    """Mean one-vs-rest rank-AUC over classes: scores (N, C) per-class
    logits/probs, labels (N,) int class ids. Classes without both a
    positive and a negative example are skipped; None when no class is
    scorable (a single-class split)."""
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels)
    aucs: List[float] = []
    for c in range(scores.shape[1]):
        pos = labels == c
        n_pos = int(pos.sum())
        n_neg = len(labels) - n_pos
        if n_pos == 0 or n_neg == 0:
            continue
        r = _ranks(scores[:, c])
        # Mann-Whitney U from the positive ranks.
        u = r[pos].sum() - n_pos * (n_pos + 1) / 2.0
        aucs.append(float(u / (n_pos * n_neg)))
    return float(np.mean(aucs)) if aucs else None


def evaluate_head(
    trunk_params,
    model_cfg: ModelConfig,
    head,
    batches: Iterable[Dict[str, np.ndarray]],
) -> Dict[str, Any]:
    """Score one head over labeled batches ({"tokens", "labels"} — the
    data/finetune_data.py / data/synthetic.make_task_batches format).
    Returns {"kind", "rows", metrics..., "score"}; predictions run
    through the serving split-apply path."""
    kind = head.task.kind
    preds: List[np.ndarray] = []
    tokens_all: List[np.ndarray] = []
    labels_all: List[np.ndarray] = []
    for batch in batches:
        out = heads_apply.predict_task_rows(
            trunk_params, model_cfg, head, batch["tokens"],
            batch.get("annotations"))
        preds.append(out)
        tokens_all.append(np.asarray(batch["tokens"]))
        labels_all.append(np.asarray(batch["labels"]))
    if not preds:
        raise ValueError("no eval batches given")
    out = np.concatenate(preds)
    tokens = np.concatenate(tokens_all)
    labels = np.concatenate(labels_all)

    metrics: Dict[str, Any] = {"kind": kind, "rows": int(len(tokens))}
    if kind == "token_classification":
        mask = (tokens != PAD_ID) & (labels >= 0)
        flat_out = out[mask]                       # (M, C)
        flat_lab = labels[mask]
        acc = float((flat_out.argmax(-1) == flat_lab).mean()) \
            if flat_lab.size else 0.0
        metrics["per_residue_accuracy"] = round(acc, 6)
        auc = auc_proxy(flat_out, flat_lab)
        if auc is not None:
            metrics["auc_proxy"] = round(auc, 6)
        metrics["score"] = metrics["per_residue_accuracy"]
    elif kind == "sequence_classification":
        acc = float((out.argmax(-1) == labels).mean())
        metrics["accuracy"] = round(acc, 6)
        auc = auc_proxy(out, labels)
        if auc is not None:
            metrics["auc_proxy"] = round(auc, 6)
        metrics["score"] = metrics["accuracy"]
    elif kind == "sequence_regression":
        pred = out[..., 0]
        target = labels.astype(np.float64)
        metrics["spearman"] = round(spearman(pred, target), 6)
        metrics["mse"] = round(float(((pred - target) ** 2).mean()), 6)
        metrics["score"] = metrics["spearman"]
    else:
        raise ValueError(f"unknown task kind {kind!r}")
    return metrics


def evaluate_heads(
    trunk_params,
    model_cfg: ModelConfig,
    heads: Iterable[Any],
    batches_for,                  # callable(LoadedHead) -> iterable of batches
    telemetry=None,
) -> Dict[str, Dict[str, Any]]:
    """Evaluate many heads against one resident trunk; emits one
    `head_eval` event per head on the telemetry stream (NULL-safe).
    Returns {head_id: metrics}."""
    from proteinbert_tpu.obs import as_telemetry

    tele = as_telemetry(telemetry)
    results: Dict[str, Dict[str, Any]] = {}
    for head in heads:
        m = evaluate_head(trunk_params, model_cfg, head,
                          batches_for(head))
        results[head.head_id] = m
        tele.emit("head_eval", head_id=head.head_id, metrics=m,
                  kind=head.task.kind, name=head.name)
    return results
