"""Versioned on-disk registry of finetuned task heads (ISSUE 8 tentpole).

The multi-tenant serving story (ROADMAP item 5) needs finetune and
serve to compose: `train/finetune.py` produces a (trunk, head) pair,
but only the HEAD is per-task — a linear/MLP layer of a few thousand
parameters over the shared trunk representation. This registry is the
artifact store that connects the two sides:

- **content-addressed**: a head's id is a digest over its parameter
  bytes + its TaskConfig + the fingerprint of the trunk it was trained
  against — two identical finetunes produce one artifact, and an id
  can never silently point at different weights;
- **self-verifying**: `meta.json` records the parameter digest; every
  `load()` recomputes it from the NPZ bytes, so a corrupted or
  hand-edited artifact raises `CorruptHeadError` instead of serving
  garbage;
- **trunk-compatible by contract**: the artifact carries the
  `trunk_fingerprint` of the trained-against trunk. Loading against a
  resident trunk whose fingerprint differs raises the typed
  `TrunkMismatchError` — a head trained on (or together with) a
  different trunk would produce plausible-looking noise, the one
  failure mode a multi-tenant platform must never be silent about.

Artifact layout (`<registry>/<head_id>/`):

    head.npz    flat arrays, slash-joined pytree paths (export.py idiom)
    meta.json   {format_version, head_id, name, kind, task, model,
                 trunk_fingerprint, head_digest, metrics, created_at}

Writes are atomic (temp dir + rename) so a crash mid-save can never
leave a loadable-but-wrong artifact. No jax import: artifacts are
saved/loaded as numpy, and device placement is the serving layer's job
(serve/dispatch.BucketDispatcher.add_head).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import tempfile
import time
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from proteinbert_tpu.configs import TaskConfig
from proteinbert_tpu.configs.config import config_from_dict, config_to_dict

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1

# Pretraining output heads are NOT part of the trunk: a finetune trunk
# (models/finetune.init drops them) and the pretrain params it came
# from must fingerprint identically.
_PRETRAIN_HEAD_KEYS = ("local_head", "global_head")


class HeadRegistryError(Exception):
    """Base class for registry failures."""


class UnknownHeadError(HeadRegistryError, LookupError):
    """No artifact with this head id (the serving layer maps this to a
    typed 404)."""


class CorruptHeadError(HeadRegistryError, ValueError):
    """An artifact's bytes do not match its recorded digest (or its
    metadata is unreadable) — refuse to serve it."""


class TrunkMismatchError(HeadRegistryError, ValueError):
    """The head was trained against a different trunk than the resident
    one; applying it would silently produce garbage."""


class UnfrozenHeadError(HeadRegistryError, ValueError):
    """`migrate_fingerprint` was asked to re-pin a head that was trained
    with `freeze_trunk=False`: its weights co-adapted to the exact trunk
    it trained with, so pinning them to a DIFFERENT trunk would be a
    silent quality lie — the typed refusal of the rollout head-migration
    contract (ISSUE 20). Re-finetune against the new trunk instead."""


def _flatten(tree: Any, path: tuple = ()) -> Dict[str, np.ndarray]:
    """Pytree of arrays → {"out/kernel": np.ndarray, ...} (sorted keys,
    fp-preserving) — the export.py flat-NPZ idiom without the jax
    dependency (np.asarray pulls device arrays to host)."""
    flat: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            flat.update(_flatten(tree[k], path + (str(k),)))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flat.update(_flatten(v, path + (str(i),)))
    else:
        flat["/".join(path)] = np.asarray(tree)
    return flat


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, arr in flat.items():
        node = tree
        keys = path.split("/")
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = arr
    return tree


def _digest(flat: Dict[str, np.ndarray]) -> str:
    """sha256 over (path, shape, dtype, raw bytes) of every leaf in
    sorted path order — the content identity of a parameter tree,
    independent of NPZ container bytes (zip timestamps vary)."""
    h = hashlib.sha256()
    for path in sorted(flat):
        a = np.ascontiguousarray(flat[path])
        h.update(path.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def trunk_fingerprint(params: Any) -> str:
    """Content fingerprint of a trunk parameter tree.

    Accepts either pretrain params (whose `local_head`/`global_head`
    pretraining output heads are dropped — they are not consumed by
    `proteinbert.encode_trunk`) or an already-stripped finetune trunk;
    both hash identically for the same weights. One device→host fetch
    of the trunk per call — compute once and keep it (the Server does).
    """
    if isinstance(params, dict):
        params = {k: v for k, v in params.items()
                  if k not in _PRETRAIN_HEAD_KEYS}
    return _digest(_flatten(params))


@dataclasses.dataclass
class LoadedHead:
    """One registered head, materialized for use: parameter pytree +
    the TaskConfig it was trained with + its metadata record."""

    head_id: str
    name: str
    task: TaskConfig
    params: Dict[str, Any]
    meta: Dict[str, Any]

    @property
    def kind(self) -> str:
        return self.task.kind


class HeadRegistry:
    """Directory-backed head artifact store (see module doc)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------- paths

    def _dir(self, head_id: str) -> str:
        if not head_id or "/" in head_id or head_id.startswith("."):
            raise UnknownHeadError(f"malformed head id {head_id!r}")
        return os.path.join(self.directory, head_id)

    # -------------------------------------------------------------- save

    def save(
        self,
        head_params: Any,
        task: TaskConfig,
        trunk_fp: str,
        *,
        name: Optional[str] = None,
        metrics: Optional[Dict[str, float]] = None,
        model: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Register one head; returns its content-addressed id.

        Saving identical (params, task, trunk) twice is idempotent —
        the second save atomically replaces an identical artifact.
        `metrics` records the finetune's eval numbers beside the
        weights (the eval harness and `pbt eval-heads` append fresh
        ones); `model` optionally records the trunk geometry the head's
        input dims came from (purely informational — compatibility is
        enforced by the trunk fingerprint, not by geometry fields).
        """
        flat = _flatten(head_params)
        if not flat:
            raise HeadRegistryError("empty head parameter tree")
        head_digest = _digest(flat)
        task_dict = config_to_dict(task)
        h = hashlib.sha256()
        h.update(head_digest.encode())
        h.update(json.dumps(task_dict, sort_keys=True).encode())
        h.update(str(trunk_fp).encode())
        head_id = h.hexdigest()[:16]
        meta = {
            "format_version": FORMAT_VERSION,
            "head_id": head_id,
            "name": name or head_id,
            "kind": task.kind,
            "task": task_dict,
            "model": model or {},
            "trunk_fingerprint": str(trunk_fp),
            "head_digest": head_digest,
            "metrics": dict(metrics or {}),
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }
        tmp = tempfile.mkdtemp(prefix=f".{head_id}.tmp.",
                               dir=self.directory)
        try:
            np.savez(os.path.join(tmp, "head.npz"), **flat)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2, sort_keys=True)
            final = self._dir(head_id)
            if os.path.isdir(final):  # idempotent re-register
                old = final + f".old.{os.getpid()}"
                os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(tmp, final)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        return head_id

    # -------------------------------------------------------------- load

    def _read_meta(self, head_id: str) -> Dict[str, Any]:
        d = self._dir(head_id)
        path = os.path.join(d, "meta.json")
        if not os.path.isdir(d) or not os.path.isfile(path):
            raise UnknownHeadError(
                f"no head {head_id!r} in registry {self.directory}")
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            raise CorruptHeadError(
                f"head {head_id}: unreadable meta.json ({e})") from None
        for field in ("format_version", "head_id", "task", "head_digest",
                      "trunk_fingerprint"):
            if field not in meta:
                raise CorruptHeadError(
                    f"head {head_id}: meta.json missing {field!r}")
        if meta["format_version"] > FORMAT_VERSION:
            raise CorruptHeadError(
                f"head {head_id}: format_version {meta['format_version']} "
                f"is newer than this build understands ({FORMAT_VERSION})")
        if meta["head_id"] != head_id:
            raise CorruptHeadError(
                f"head {head_id}: meta.json claims id {meta['head_id']!r}")
        return meta

    def load(self, head_id: str,
             trunk_fp: Optional[str] = None) -> LoadedHead:
        """Load + verify one head. With `trunk_fp` (the resident trunk's
        fingerprint), a trained-against-a-different-trunk artifact
        raises TrunkMismatchError BEFORE any weights are returned."""
        meta = self._read_meta(head_id)
        if trunk_fp is not None and meta["trunk_fingerprint"] != trunk_fp:
            raise TrunkMismatchError(
                f"head {head_id} ({meta.get('name')}) was trained against "
                f"trunk {meta['trunk_fingerprint'][:12]}…, but the resident "
                f"trunk fingerprints as {str(trunk_fp)[:12]}… — applying it "
                "would silently produce garbage. Re-finetune against this "
                "trunk (freeze_trunk keeps the fingerprint stable), or "
                "serve the trunk this head was trained with.")
        npz_path = os.path.join(self._dir(head_id), "head.npz")
        try:
            with np.load(npz_path) as z:
                flat = {k: np.array(z[k]) for k in z.files}
        except (OSError, ValueError, KeyError,
                zipfile.BadZipFile) as e:
            raise CorruptHeadError(
                f"head {head_id}: unreadable head.npz ({e})") from None
        got = _digest(flat)
        if got != meta["head_digest"]:
            raise CorruptHeadError(
                f"head {head_id}: parameter digest {got[:12]}… does not "
                f"match the recorded {meta['head_digest'][:12]}… — the "
                "artifact is corrupted; refusing to serve it")
        task = config_from_dict(meta["task"], TaskConfig)
        return LoadedHead(head_id=head_id, name=meta.get("name", head_id),
                          task=task, params=_unflatten(flat), meta=meta)

    # ----------------------------------------------------------- migrate

    def migrate_fingerprint(self, head_id: str, new_trunk_fp: str,
                            note: Optional[str] = None) -> Dict[str, Any]:
        """Re-pin one registered head to a new trunk fingerprint
        (blue-green rollout promotion, ISSUE 20) with an audit trail.

        Only FROZEN-trunk heads migrate: a head trained with
        `freeze_trunk=True` is a function of the trunk's OUTPUT SPACE,
        and the rollout gate (`heads_eval_score_min` delta through the
        candidate trunk) has measured that space before any promotion;
        an unfrozen head co-adapted to its exact trunk and gets the
        typed `UnfrozenHeadError` instead. The rewrite is in-place and
        atomic (tmp file + os.replace), keeps the head_id (the
        directory name stays the content address of the ORIGINAL
        registration — `_read_meta` checks identity against the
        directory, and `load()` verifies weights by digest, so an
        artifact can never silently point at different weights), and
        appends one {from, to, at, note} record to `meta["migrations"]`.
        Returns the updated meta. Idempotent when already pinned to
        `new_trunk_fp`."""
        meta = self._read_meta(head_id)
        task = config_from_dict(meta["task"], TaskConfig)
        if not task.freeze_trunk:
            raise UnfrozenHeadError(
                f"head {head_id} ({meta.get('name')}) was trained with "
                "freeze_trunk=False — its weights co-adapted to trunk "
                f"{meta['trunk_fingerprint'][:12]}… and cannot be "
                "re-pinned to a different trunk; re-finetune it against "
                "the new trunk instead")
        old_fp = meta["trunk_fingerprint"]
        if old_fp == str(new_trunk_fp):
            return meta
        meta["trunk_fingerprint"] = str(new_trunk_fp)
        meta.setdefault("migrations", []).append({
            "from": old_fp,
            "to": str(new_trunk_fp),
            "at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "note": note or "",
        })
        path = os.path.join(self._dir(head_id), "meta.json")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return meta

    def verify(self, head_id: str) -> Dict[str, Any]:
        """Full integrity check (meta readable + digest matches);
        returns the meta record. Raises UnknownHeadError /
        CorruptHeadError like load()."""
        return self.load(head_id).meta

    # -------------------------------------------------------------- list

    def list_heads(self) -> List[Dict[str, Any]]:
        """Metadata of every well-formed artifact, oldest first.
        Malformed entries are skipped with a warning (listing must work
        on an imperfect store; load() is where corruption is typed)."""
        out = []
        for entry in sorted(os.listdir(self.directory)):
            if entry.startswith("."):
                continue
            try:
                out.append(self._read_meta(entry))
            except (UnknownHeadError, CorruptHeadError) as e:
                logger.warning("skipping registry entry %s: %s", entry, e)
        out.sort(key=lambda m: (m.get("created_at") or "", m["head_id"]))
        return out

    def __contains__(self, head_id: str) -> bool:
        try:
            self._read_meta(head_id)
            return True
        except HeadRegistryError:
            return False
