"""Split-apply execution: one shared trunk pass, many cheap head tails.

The multi-tenant serving shape (ISSUE 8): a micro-batch of requests for
DIFFERENT finetuned tasks runs the expensive trunk forward ONCE —
`trunk_batch` is one jitted executable per (batch_class, bucket_len)
shape, independent of which heads ride the batch — and each distinct
head then runs as a cheap jitted matmul tail over the full batch
(`head_batch`), with each request keeping its own head's row. Head
parameters are traced arguments, so every head of the same structure
(linear vs one-hidden-layer MLP, same dims, same task kind) shares ONE
compiled head executable: adding a tenant never adds a trunk compile
and usually adds no compile at all.

Numerics contract: `head_batch` composes `models/finetune.apply_head`
over `models/proteinbert.encode_trunk` — the exact decomposition the
monolithic `models/finetune.apply` is built from — so split-apply
output is the same computation, and a row's result is independent of
which other rows (other tenants' requests) share its batch (per-row
independence of the trunk forward; tests/test_heads.py asserts bit
identity of mixed-batch vs per-head serving).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.models import proteinbert


@partial(jax.jit, static_argnames="cfg")
def trunk_batch(params, tokens, annotations, cfg: ModelConfig):
    """The shared executable: (B, L) tokens + (B, A) annotations →
    {"local", "global", "pad_mask"} trunk representation. One compile
    per (B, L) shape regardless of which heads consume it."""
    return proteinbert.encode_trunk(params, tokens, cfg, annotations)


@partial(jax.jit, static_argnames="kind")
def head_batch(head, local, global_, pad_mask, kind: str):
    """One head's tail over a whole trunk-encoded batch: float32
    logits/predictions shaped by `kind` (models/finetune module doc).
    `head` is a traced pytree — all heads with one structure share one
    executable."""
    return ft_model.apply_head(head, local, global_, pad_mask, kind)


def apply_heads(
    trunk_out: Dict[str, jax.Array],
    heads: Sequence[Any],
) -> List[np.ndarray]:
    """Mixed-head tail: per-row head objects (each with `.params`,
    `.task.kind`, `.head_id` — heads/registry.LoadedHead) over one
    shared trunk representation. Each DISTINCT head runs once over the
    full batch (shape-stable: no per-group-size executables), then
    every row keeps its own head's output. Returns host arrays aligned
    to the input rows."""
    rows_out: List[Optional[np.ndarray]] = [None] * len(heads)
    by_head: Dict[str, List[int]] = {}
    head_of: Dict[str, Any] = {}
    for i, head in enumerate(heads):
        by_head.setdefault(head.head_id, []).append(i)
        head_of[head.head_id] = head
    for head_id, idxs in by_head.items():
        head = head_of[head_id]
        out = np.asarray(head_batch(head.params, trunk_out["local"],
                                    trunk_out["global"],
                                    trunk_out["pad_mask"],
                                    head.task.kind))
        for i in idxs:
            rows_out[i] = out[i]
    return rows_out  # type: ignore[return-value]


def predict_task_rows(
    trunk_params,
    cfg: ModelConfig,
    head,
    tokens: np.ndarray,
    annotations: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Offline single-head entry: (N, L) tokens → (N, ...) float32 head
    outputs through the SAME jitted trunk+head executables serving
    uses — the sequential-per-head reference mixed-batch parity is
    measured against, and the eval harness's forward."""
    if annotations is None:
        annotations = np.zeros((tokens.shape[0], cfg.num_annotations),
                               np.float32)
    trunk_out = trunk_batch(trunk_params, tokens, annotations, cfg)
    return np.asarray(head_batch(head.params, trunk_out["local"],
                                 trunk_out["global"],
                                 trunk_out["pad_mask"], head.task.kind))
