"""Split-apply execution: one shared trunk pass, many cheap head tails.

The multi-tenant serving shape (ISSUE 8): a micro-batch of requests for
DIFFERENT finetuned tasks runs the expensive trunk forward ONCE —
`trunk_batch` is one jitted executable per (batch_class, bucket_len)
shape, independent of which heads ride the batch — and each distinct
head then runs as a cheap jitted matmul tail over the full batch
(`head_batch`), with each request keeping its own head's row. Head
parameters are traced arguments, so every head of the same structure
(linear vs one-hidden-layer MLP, same dims, same task kind) shares ONE
compiled head executable: adding a tenant never adds a trunk compile
and usually adds no compile at all.

Numerics contract: `head_batch` composes `models/finetune.apply_head`
over `models/proteinbert.encode_trunk` — the exact decomposition the
monolithic `models/finetune.apply` is built from — so split-apply
output is the same computation, and a row's result is independent of
which other rows (other tenants' requests) share its batch (per-row
independence of the trunk forward; tests/test_heads.py asserts bit
identity of mixed-batch vs per-head serving).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.models import proteinbert


@partial(jax.jit, static_argnames="cfg")
def trunk_batch(params, tokens, annotations, cfg: ModelConfig):
    """The shared executable: (B, L) tokens + (B, A) annotations →
    {"local", "global", "pad_mask"} trunk representation. One compile
    per (B, L) shape regardless of which heads consume it."""
    return proteinbert.encode_trunk(params, tokens, cfg, annotations)


@partial(jax.jit, static_argnames="kind")
def head_batch(head, local, global_, pad_mask, kind: str):
    """One head's tail over a whole trunk-encoded batch: float32
    logits/predictions shaped by `kind` (models/finetune module doc).
    `head` is a traced pytree — all heads with one structure share one
    executable."""
    return ft_model.apply_head(head, local, global_, pad_mask, kind)


@partial(jax.jit, static_argnames="cfg")
def packed_trunk_batch(params, tokens, segment_ids, annotations,
                       cfg: ModelConfig):
    """The ragged-serving shared executable (ISSUE 9): one fixed-shape
    (rows, seq_len) PACKED batch → {"local" (B, L, C), "global"
    (B, S, G), "seg_mask" (B, S, L) bool} per-segment trunk
    representation. One compile per request-kind shape regardless of
    which heads consume it — the packed sibling of `trunk_batch`.
    `seg_mask` is True only at a segment's REAL token positions (a
    bucket-quantized span's <pad> tail is excluded), so the head tails
    pool exactly the positions the bucketed path's pad_mask keeps.
    Under cfg.use_pallas the trunk's local track runs the segment-
    aware fused Pallas kernel on supported shapes (ISSUE 10) — the
    shared packed trunk executable is a fast-path executable."""
    from proteinbert_tpu import inference
    from proteinbert_tpu.data.vocab import PAD_ID

    local, global_ = proteinbert.encode(params, tokens, annotations, cfg,
                                        pad_mask=(tokens != PAD_ID),
                                        segment_ids=segment_ids)
    return {"local": local, "global": global_,
            "seg_mask": inference._segment_real_mask(
                tokens, segment_ids, annotations.shape[1])}


def packed_head_features(local: jax.Array, global_: jax.Array,
                         seg_mask: jax.Array, kind: str) -> jax.Array:
    """Per-SEGMENT feature tensor for a `kind` head over a packed trunk
    representation — the segment-aware sibling of
    `models/finetune.head_features` (same pooling math per segment:
    mask-weighted mean over real positions, concatenated with the
    segment's own global vector), so a span's head input matches the
    bucketed path's within jitted tolerance. token_classification heads
    read the local track directly; callers slice each segment's span
    from the (B, L, out) result."""
    if kind == "token_classification":
        return local
    m = seg_mask.astype(local.dtype)  # (B, S, L)
    pooled = (jnp.einsum("bsl,blc->bsc", m, local)
              / jnp.maximum(m.sum(-1)[..., None], 1.0))
    return jnp.concatenate([global_, pooled], axis=-1)


@partial(jax.jit, static_argnames="kind")
def packed_head_batch(head, local, global_, seg_mask, kind: str):
    """One head's tail over a packed trunk batch: float32 outputs shaped
    (B, L, out) for token_classification (slice spans out) or (B, S,
    out) per segment otherwise. `head` is traced — all heads of one
    structure share one executable, same as `head_batch`."""
    return ft_model._head_apply(
        head, packed_head_features(local, global_, seg_mask, kind)
    ).astype(jnp.float32)


def apply_heads_packed(
    trunk_out: Dict[str, jax.Array],
    riders: Sequence[Tuple[Any, int, int, int, int]],
) -> List[np.ndarray]:
    """Mixed-head tail for a PACKED batch: `riders` is one (head, row,
    segment_index, start, span) tuple per request, row-major. Each
    DISTINCT head runs once over the full packed batch, then every
    rider keeps its own segment's slice — (span, out) for
    token_classification (aligned with the bucketed (bucket_len, out)
    output), (out,) / (1,) otherwise. Returns host arrays aligned to
    `riders` order."""
    out: List[Optional[np.ndarray]] = [None] * len(riders)
    by_head: Dict[str, List[int]] = {}
    head_of: Dict[str, Any] = {}
    for i, (head, _, _, _, _) in enumerate(riders):
        by_head.setdefault(head.head_id, []).append(i)
        head_of[head.head_id] = head
    for head_id, idxs in by_head.items():
        head = head_of[head_id]
        res = np.asarray(packed_head_batch(
            head.params, trunk_out["local"], trunk_out["global"],
            trunk_out["seg_mask"], head.task.kind))
        for i in idxs:
            _, row, seg, start, span = riders[i]
            if head.task.kind == "token_classification":
                out[i] = res[row, start:start + span]
            else:
                out[i] = res[row, seg]
    return out  # type: ignore[return-value]


def apply_heads(
    trunk_out: Dict[str, jax.Array],
    heads: Sequence[Any],
) -> List[np.ndarray]:
    """Mixed-head tail: per-row head objects (each with `.params`,
    `.task.kind`, `.head_id` — heads/registry.LoadedHead) over one
    shared trunk representation. Each DISTINCT head runs once over the
    full batch (shape-stable: no per-group-size executables), then
    every row keeps its own head's output. Returns host arrays aligned
    to the input rows."""
    rows_out: List[Optional[np.ndarray]] = [None] * len(heads)
    by_head: Dict[str, List[int]] = {}
    head_of: Dict[str, Any] = {}
    for i, head in enumerate(heads):
        by_head.setdefault(head.head_id, []).append(i)
        head_of[head.head_id] = head
    for head_id, idxs in by_head.items():
        head = head_of[head_id]
        out = np.asarray(head_batch(head.params, trunk_out["local"],
                                    trunk_out["global"],
                                    trunk_out["pad_mask"],
                                    head.task.kind))
        for i in idxs:
            rows_out[i] = out[i]
    return rows_out  # type: ignore[return-value]


def predict_task_rows(
    trunk_params,
    cfg: ModelConfig,
    head,
    tokens: np.ndarray,
    annotations: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Offline single-head entry: (N, L) tokens → (N, ...) float32 head
    outputs through the SAME jitted trunk+head executables serving
    uses — the sequential-per-head reference mixed-batch parity is
    measured against, and the eval harness's forward."""
    if annotations is None:
        annotations = np.zeros((tokens.shape[0], cfg.num_annotations),
                               np.float32)
    trunk_out = trunk_batch(trunk_params, tokens, annotations, cfg)
    return np.asarray(head_batch(head.params, trunk_out["local"],
                                 trunk_out["global"],
                                 trunk_out["pad_mask"], head.task.kind))
