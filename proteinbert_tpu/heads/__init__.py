"""Multi-tenant head registry + split-apply + downstream eval (ISSUE 8).

The subsystem that turns "a pretraining repro with a server" into "a
task platform" (ROADMAP item 5): many small finetuned task heads —
secondary structure, GO prediction, stability, arbitrary user tasks —
share ONE resident pretrained trunk's worth of HBM, and the serving
layer batches requests for *different* heads through the shared trunk
in one micro-batch, swapping only the cheap head matmuls.

- **registry** (`heads/registry.py`) — content-addressed, self-
  verifying on-disk head artifacts: head params + TaskConfig + trunk
  fingerprint + eval metrics. Typed failures: `UnknownHeadError`
  (serving 404), `CorruptHeadError` (digest mismatch),
  `TrunkMismatchError` (trained against a different trunk — the
  silent-garbage case, refused).
- **apply** (`heads/apply.py`) — split-apply execution: one jitted
  trunk executable per batch shape shared by ALL heads
  (`proteinbert.encode_trunk` under the hood), plus a cheap jitted
  per-head tail reusing `models/finetune.apply_head`.
- **eval** (`heads/eval.py`) — downstream-task metrics (per-residue
  accuracy, multilabel AUC proxy, regression Spearman) recorded as
  schema-versioned `head_eval` events so finetune-quality regressions
  gate via the bench-trajectory sentinel like perf does.

Producers: `train/finetune.finetune(..., registry=)` and the
`pbt finetune --register-head` CLI. Consumers: the serving layer
(`serve/dispatch.py` dynamic head kinds, `Server.predict_task`),
`pbt eval-heads`, and `bench.py --heads`. docs/finetuning.md walks the
train → register → serve → eval loop end to end.
"""

from proteinbert_tpu.heads.registry import (
    CorruptHeadError,
    HeadRegistry,
    HeadRegistryError,
    LoadedHead,
    TrunkMismatchError,
    UnknownHeadError,
    trunk_fingerprint,
)

__all__ = [
    "HeadRegistry",
    "LoadedHead",
    "HeadRegistryError",
    "UnknownHeadError",
    "CorruptHeadError",
    "TrunkMismatchError",
    "trunk_fingerprint",
]
