"""Segment-aware sequence packing (ISSUE 4 tentpole).

UniRef sequences cluster around ~350 residues while the padded training
row is 1024-8192, so even the bucketed iterator spends most of a step's
FLOPs and HBM traffic on `<pad>`. This module packs SEVERAL proteins
into one fixed-shape row and tags every position with a segment id, so
the model keeps ONE compiled shape (no bucket-fill stalls, no per-bucket
executables) while almost every position is a real residue — the
ragged-input strategy TPU stacks converge on (Ragged Paged Attention,
arXiv:2604.15464).

A packed batch is:

    tokens       (B, L)    int32 — each row is the concatenation of the
                           nonpad tokens (<sos> seq <eos>) of up to S
                           proteins, padded with <pad>=0 at the tail;
    segment_ids  (B, L)    int32 — 0 at pad, 1..S at the positions of
                           the row's 1st..S-th protein;
    annotations  (B, S, A) float32 — one annotation vector per packed
                           protein (zero rows for unused slots).

Downstream, every cross-position op is segment-masked (models/
proteinbert.py packed path; kernels/fused_block.local_track_segment_
reference; ops/attention.packed_global_attention_apply) and the loss
normalizes per segment (train/loss.packed_pretrain_loss), so a packed
row is numerically a batch of independent proteins — proven by the
leakage/parity tests in tests/test_packing.py.

Packing plan (`PackPlanner`): greedy FIRST-FIT over a bounded set of
open rows. Sequences arrive in epoch-permutation order; each goes into
the first open row with enough remaining capacity and a free segment
slot, else opens a new row. When the open set exceeds its bound the
OLDEST row is closed (emitted) — a pure streaming rule, so the whole
plan is a deterministic function of (lengths, seed, epoch order):
identical on every host (multi-host lockstep, same contract as
make_bucketed_iterator) and identical on restart (`skip_batches`
replays only the cheap index bookkeeping, no data is fetched).

Per-batch `pad_fraction` is reported to the obs metrics registry under
the SAME metric name the bucketed iterator uses (`data_pad_fraction`,
labeled by strategy), so `pbt diagnose` can compare the two strategies
from one stream.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from proteinbert_tpu.data.dataset import _check_per_host, _epoch_order, _make_fetch
from proteinbert_tpu.data.vocab import PAD_ID

# A closed row slot below this many free positions cannot hold even an
# empty tokenized sequence (<sos><eos>), so the planner closes it early.
_MIN_FIT = 2


class PackPlanner:
    """Greedy first-fit packer over a bounded set of open rows.

    add(row_id, length) -> list of CLOSED rows (each a list of row ids),
    in deterministic closing order; flush() closes everything left.
    Pure index bookkeeping — no data moves through the planner, which is
    what makes multi-host lockstep and free restart replay possible.
    """

    def __init__(self, seq_len: int, max_segments: int, max_open: int):
        if max_segments < 1:
            raise ValueError(f"max_segments must be >= 1, got {max_segments}")
        if max_open < 1:
            raise ValueError(f"max_open must be >= 1, got {max_open}")
        self.seq_len = seq_len
        self.max_segments = max_segments
        self.max_open = max_open
        # Each open row: [remaining_capacity, [row_ids...]]
        self._open: List[List] = []

    def add(self, row_id: int, length: int) -> List[List[int]]:
        length = int(min(length, self.seq_len))
        closed: List[List[int]] = []
        placed = None
        for slot in self._open:
            if slot[0] >= length and len(slot[1]) < self.max_segments:
                slot[0] -= length
                slot[1].append(row_id)
                placed = slot
                break
        if placed is None:
            placed = [self.seq_len - length, [row_id]]
            self._open.append(placed)
            if len(self._open) > self.max_open:
                # max_open >= 1, so the popped oldest is never `placed`
                # (which was just appended at the end).
                closed.append(self._open.pop(0)[1])
        # A row that can't take another sequence only wastes first-fit
        # scans — close it now (also bounds per-row segment count).
        if (placed[0] < _MIN_FIT
                or len(placed[1]) >= self.max_segments):
            self._open = [s for s in self._open if s is not placed]
            closed.append(placed[1])
        return closed

    def flush(self) -> List[List[int]]:
        closed = [slot[1] for slot in self._open]
        self._open = []
        return closed


class OnlinePacker:
    """Incremental first-fit packer for ONLINE serving (ISSUE 9).

    The serving-side sibling of `PackPlanner`: the same first-fit
    residual-capacity placement rule, but items carry PAYLOADS (admitted
    requests) and rows are taken by the caller's dispatch policy (the
    ragged scheduler pops the oldest rows at batch formation) instead of
    closing on a streaming bound. Placement is O(open rows) per item and
    deterministic in arrival order, so packed-batch composition is a
    pure function of (arrival order, spans, pops) — the property the
    fake-clock formation tests rely on.

    Each open row tracks `residual` capacity out of `seq_len` and an
    ordered `items` list of (payload, start, span) triples; a row takes
    a new item when `residual >= span` and it holds fewer than
    `max_segments` items. Rows pop oldest-first; because items arrive in
    order and rows are created in order, the FIRST item of the FIRST row
    is always the oldest pending payload (the deadline-trigger anchor).
    """

    __slots__ = ("seq_len", "max_segments", "_rows")

    def __init__(self, seq_len: int, max_segments: int):
        if max_segments < 1:
            raise ValueError(f"max_segments must be >= 1, got {max_segments}")
        if seq_len < _MIN_FIT:
            raise ValueError(f"seq_len must be >= {_MIN_FIT}, got {seq_len}")
        self.seq_len = int(seq_len)
        self.max_segments = int(max_segments)
        # Each row: [residual, [(payload, start, span), ...]]
        self._rows: List[List] = []

    def __len__(self) -> int:
        """Open row count."""
        return len(self._rows)

    def total_items(self) -> int:
        return sum(len(r[1]) for r in self._rows)

    def place(self, payload, span: int) -> int:
        """First-fit one item; returns the row index it landed in."""
        span = int(span)
        if not 0 < span <= self.seq_len:
            raise ValueError(f"span {span} not in (0, {self.seq_len}]")
        for i, row in enumerate(self._rows):
            if row[0] >= span and len(row[1]) < self.max_segments:
                row[1].append((payload, self.seq_len - row[0], span))
                row[0] -= span
                return i
        self._rows.append([self.seq_len - span, [(payload, 0, span)]])
        return len(self._rows) - 1

    def row_heads(self) -> List:
        """The first (oldest) payload of every open row. Items within a
        row stay in arrival order (even across `expire`), so the oldest
        pending payload overall is always among these — what the
        max-wait dispatch trigger scans."""
        return [row[1][0][0] for row in self._rows]

    def expire(self, predicate) -> List:
        """Remove every item whose payload satisfies `predicate` and
        drop rows that become empty; returns the removed payloads. A
        removed item's span stays dead space in its row (residual is
        NOT returned) — holes cost capacity, not correctness."""
        removed: List = []
        rows: List[List] = []
        for row in self._rows:
            kept = []
            for item in row[1]:
                if predicate(item[0]):
                    removed.append(item[0])
                else:
                    kept.append(item)
            if kept:
                row[1] = kept
                rows.append(row)
        self._rows = rows
        return removed

    def pop_rows(self, n: int) -> List[List[Tuple]]:
        """Take the oldest `n` rows (fewer if fewer are open); each is
        the row's ordered [(payload, start, span), ...] list."""
        taken, self._rows = self._rows[:n], self._rows[n:]
        return [row[1] for row in taken]

    def drain_items(self) -> List:
        """Abort path: every pending payload, row-major, and reset."""
        items = [p for _, row in self._rows for p, _, _ in row]
        self._rows = []
        return items


def pack_rows(
    fetched_tokens: np.ndarray,
    fetched_annotations: np.ndarray,
    groups: List[List[int]],
    seq_len: int,
    max_segments: int,
) -> Dict[str, np.ndarray]:
    """Assemble fetched per-sequence arrays into a packed batch.

    `groups[i]` lists positions into `fetched_*` for packed row i (the
    planner guarantees their nonpad lengths fit seq_len and there are at
    most max_segments of them).
    """
    B = len(groups)
    A = fetched_annotations.shape[-1]
    tokens = np.zeros((B, seq_len), dtype=np.int32)
    segment_ids = np.zeros((B, seq_len), dtype=np.int32)
    annotations = np.zeros((B, max_segments, A), dtype=np.float32)
    for i, group in enumerate(groups):
        cursor = 0
        for s, pos in enumerate(group):
            row = fetched_tokens[pos]
            n = int((row != PAD_ID).sum())
            n = min(n, seq_len - cursor)
            tokens[i, cursor:cursor + n] = row[:n]
            segment_ids[i, cursor:cursor + n] = s + 1
            annotations[i, s] = fetched_annotations[pos]
            cursor += n
    return {"tokens": tokens, "segment_ids": segment_ids,
            "annotations": annotations}


def pad_fraction(tokens: np.ndarray) -> float:
    """Fraction of pad positions in a (B, L) token batch."""
    return float((tokens == PAD_ID).mean())


def make_packed_iterator(
    dataset,
    batch_size: int,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    skip_batches: int = 0,
    max_segments: int = 8,
    max_open: int = 0,
    metrics=None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or num_epochs-bounded) per-host PACKED batch iterator.

    Yields {"tokens" (B, L), "segment_ids" (B, L), "annotations"
    (B, S, A)} per-host batches (B = batch_size, L = dataset.seq_len,
    S = max_segments). Multi-host lockstep mirrors
    make_bucketed_iterator: every host runs the SAME planner over the
    same epoch permutation (identical seed), so all hosts agree on the
    packing plan; when `batch_size * process_count` rows are ready each
    host fetches only its slice.

    `max_open` bounds the planner's open-row set (0 = auto:
    2 * global batch — enough look-back that a long sequence arriving
    late still finds a half-empty row). `skip_batches` replays only the
    planner bookkeeping — resume costs index arithmetic, not I/O.

    `metrics` (an obs.MetricsRegistry) receives per-batch
    `data_pad_fraction{strategy="packed"}` plus segment/dropped-row
    counters; None = no reporting.
    """
    n = len(dataset)
    per_host = _check_per_host(n, batch_size, process_count)
    global_batch = batch_size * process_count
    if max_open <= 0:
        max_open = 2 * global_batch
    lengths = np.minimum(dataset.row_lengths(), dataset.seq_len)
    seq_len = dataset.seq_len
    block = getattr(dataset, "shuffle_block", None)
    fetch = _make_fetch(dataset)
    rng = np.random.default_rng(seed)

    gauge = counter_seg = counter_rows = counter_drop = None
    if metrics is not None:
        gauge = metrics.gauge("data_pad_fraction", strategy="packed")
        counter_seg = metrics.counter("data_packed_segments_total")
        counter_rows = metrics.counter("data_packed_rows_total")
        counter_drop = metrics.counter("data_dropped_rows_total",
                                       strategy="packed")

    planner = PackPlanner(seq_len, max_segments, max_open)
    ready: List[List[int]] = []

    def emit(groups: List[List[int]], epoch: int):
        mine = groups[process_index * batch_size
                      : (process_index + 1) * batch_size]
        flat = [r for g in mine for r in g]
        # Map each group's row ids to positions in the flattened fetch.
        pos = 0
        positions = []
        for g in mine:
            positions.append(list(range(pos, pos + len(g))))
            pos += len(g)
        data = fetch(np.asarray(flat, dtype=np.int64), epoch)
        batch = pack_rows(data["tokens"], data["annotations"], positions,
                          seq_len, max_segments)
        if metrics is not None:
            gauge.set(pad_fraction(batch["tokens"]))
            counter_seg.inc(len(flat))
            counter_rows.inc(len(mine))
        return batch

    epoch = 0
    while num_epochs is None or epoch < num_epochs:
        order = _epoch_order(n, rng, shuffle, block)[: per_host * process_count]
        for i in order:
            ready.extend(planner.add(int(i), int(lengths[i])))
            while len(ready) >= global_batch:
                groups, ready = ready[:global_batch], ready[global_batch:]
                if skip_batches > 0:
                    skip_batches -= 1
                    continue
                yield emit(groups, epoch)
        epoch += 1
    # End of data: flush the planner and emit every FULL global batch;
    # the (sub-global-batch) remainder cannot be emitted at a static
    # shape — count it instead of losing it silently.
    ready.extend(planner.flush())
    while len(ready) >= global_batch:
        groups, ready = ready[:global_batch], ready[global_batch:]
        if skip_batches > 0:
            skip_batches -= 1
            continue
        yield emit(groups, epoch - 1 if epoch else 0)
    dropped = sum(len(g) for g in ready)
    if dropped:
        if counter_drop is not None:
            counter_drop.inc(dropped)
        import logging

        logging.getLogger(__name__).warning(
            "packed iterator ended with %d pending sequences in %d "
            "partial rows (a sub-global-batch remainder cannot be "
            "emitted at a static shape); counted in "
            "data_dropped_rows_total", dropped, len(ready))


def unpack_segments(
    batch: Dict[str, np.ndarray],
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Split a packed batch back into per-sequence (tokens, annotation)
    pairs, in row-major segment order — the inverse the parity tests and
    debugging tools use."""
    out = []
    tokens, seg, ann = (batch["tokens"], batch["segment_ids"],
                        batch["annotations"])
    for b in range(tokens.shape[0]):
        n_seg = int(seg[b].max())
        for s in range(1, n_seg + 1):
            mask = seg[b] == s
            out.append((tokens[b][mask], ann[b, s - 1]))
    return out
