"""Host-side tokenization transforms (reference C6a/C6b).

The reference runs tokenize → random-crop → randomize → pad per sample in
DataLoader workers (reference data_processing.py:159-180). On TPU the host
is often a single weak core per chip, so this module does only the cheap,
string-shaped work — crop / tokenize / pad to a static length — vectorized
in numpy. The stochastic corruption (token randomization, annotation
masking) runs ON DEVICE inside the jitted train step (see
data/corruption.py), which the reference cannot do.

Semantics notes vs the reference:
- The reference crops the *tokenized* sequence (reference
  data_processing.py:64-83), so <sos>/<eos> can be cropped away. Here we
  crop the raw residues to seq_len-2 and then always add <sos>/<eos> —
  paper-faithful framing, and it gives the model a deterministic sentinel
  at both ends.
- Padding always uses <pad>=0. (The reference's per-sample ToTensor default
  would have padded with an out-of-vocab id had it ever padded — SURVEY
  ledger #10.)
- Crop windows are COUNTER-BASED, not drawn from a stateful RNG: the
  window for a row is a pure function of (crop_seed, row_id) via
  splitmix64, identical on the numpy and C++ (native/tokenizer.cpp)
  paths. With the per-epoch seed derived by `epoch_crop_seed`, a resumed
  run reproduces the exact crop windows of an uninterrupted one — the
  reference replays data from scratch on resume (reference
  utils.py:267-282) and round 1 of this build replayed row indices but
  not windows (VERDICT r1 Weak #3; both beaten here).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID, get_vocab

_U64 = np.uint64


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over uint64 — the SAME mix the native
    tokenizer uses (tokenizer.cpp), so numpy and C++ crops agree bit-for-
    bit."""
    with np.errstate(over="ignore"):
        x = (np.asarray(x, _U64) + _U64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)
        return x ^ (x >> _U64(31))


def epoch_crop_seed(base_seed: int, epoch: int) -> int:
    """Per-epoch window seed: same row gets a FRESH window each epoch but
    the same window every time (epoch, row) repeats — e.g. on checkpoint
    resume."""
    with np.errstate(over="ignore"):
        mixed = splitmix64(
            _U64(base_seed & 0xFFFFFFFFFFFFFFFF)
            + _U64(0xD1B54A32D192ED03) * _U64(epoch)
        )
    return int(mixed)


def crop_starts(
    lengths: np.ndarray, cap: int, crop_seed: int, row_ids: np.ndarray
) -> np.ndarray:
    """(B,) window starts: splitmix64(seed + row_id) % (len - cap + 1)
    for rows longer than `cap`, 0 otherwise. Mirrors tokenizer.cpp.

    Divergence note: the span is len-cap+1 INCLUSIVE of the final legal
    window. The reference's SentenceRandomCrop never samples the last
    window (torch.randint's high is exclusive, reference
    data_processing.py:82) — a deliberate off-by-one fix here, so the
    sequence tail is reachable."""
    lengths = np.asarray(lengths, np.int64)
    with np.errstate(over="ignore"):
        r = splitmix64(_U64(crop_seed & 0xFFFFFFFFFFFFFFFF)
                       + np.asarray(row_ids, _U64))
    span = np.maximum(lengths - cap + 1, 1).astype(np.uint64)
    return np.where(lengths > cap, (r % span).astype(np.int64), 0)


def crop_start(length: int, cap: int, crop_seed: int, row_id: int = 0) -> int:
    """Scalar form of `crop_starts` for single-row callers."""
    return int(crop_starts(np.array([length]), cap, crop_seed,
                           np.array([row_id]))[0])


def random_crop(
    seq: str, max_residues: int, crop_seed: int, row_id: int = 0
) -> str:
    """The counter-based window of `max_residues` for (crop_seed, row_id)
    (reference data_processing.py:64-83's random crop, made a pure
    function of its inputs)."""
    if len(seq) <= max_residues:
        return seq
    start = crop_start(len(seq), max_residues, crop_seed, row_id)
    return seq[start : start + max_residues]


def _encode_row(out_row: np.ndarray, seq: str, cap: int, start: int, vocab) -> None:
    """Shared crop→encode→sos/eos body of `tokenize` and the numpy path of
    `tokenize_batch` — ONE copy so the two paths cannot drift (they are
    parity-tested against each other and against the C++ kernel)."""
    if len(seq) > cap:
        seq = seq[start : start + cap]
    ids = vocab.encode(seq)
    out_row[0] = SOS_ID
    out_row[1 : 1 + len(ids)] = ids
    out_row[1 + len(ids)] = EOS_ID


def tokenize(
    seq: str,
    seq_len: int,
    crop_seed: Optional[int] = None,
    row_id: int = 0,
) -> np.ndarray:
    """Crop → encode → add <sos>/<eos> → pad to `seq_len`. Returns
    (seq_len,) int32. With `crop_seed`, long sequences take the
    counter-based window for (crop_seed, row_id); else head-truncate."""
    cap = seq_len - 2
    start = (crop_start(len(seq), cap, crop_seed, row_id)
             if crop_seed is not None and len(seq) > cap else 0)
    out = np.full(seq_len, PAD_ID, dtype=np.int32)
    _encode_row(out, seq, cap, start, get_vocab())
    return out


_NATIVE_MIN_BATCH = 8  # below this the ctypes call overhead wins


def tokenize_batch(
    seqs: Sequence[str],
    seq_len: int,
    crop_seed: Optional[int] = None,
    row_ids: Optional[np.ndarray] = None,
    use_native: Optional[bool] = None,
) -> np.ndarray:
    """Tokenize a list of sequences to a dense (B, seq_len) int32 batch.

    Real batches dispatch to the C++ kernel (native/tokenizer.cpp) when it
    is available — same output contract AND identical crop windows (both
    paths compute splitmix64(crop_seed + row_id)); pass use_native=False
    to force the numpy path. `row_ids` defaults to 0..B-1; datasets pass
    global row indices so a row's window is independent of which batch it
    lands in.
    """
    if row_ids is None:
        row_ids = np.arange(len(seqs), dtype=np.int64)
    else:
        row_ids = np.asarray(row_ids, dtype=np.int64)
        if len(row_ids) != len(seqs):
            raise ValueError(f"{len(row_ids)} row_ids for {len(seqs)} seqs")
    if use_native is None:
        use_native = len(seqs) >= _NATIVE_MIN_BATCH
    if use_native:
        from proteinbert_tpu.native import tokenize_batch_native

        out = tokenize_batch_native(seqs, seq_len, crop_seed, row_ids)
        if out is not None:
            return out
    cap = seq_len - 2
    out = np.full((len(seqs), seq_len), PAD_ID, dtype=np.int32)
    if crop_seed is not None:
        lengths = np.fromiter((len(s) for s in seqs), np.int64, len(seqs))
        starts = crop_starts(lengths, cap, crop_seed, row_ids)
    else:
        starts = np.zeros(len(seqs), np.int64)
    vocab = get_vocab()
    for i, s in enumerate(seqs):
        _encode_row(out[i], s, cap, int(starts[i]), vocab)
    return out
