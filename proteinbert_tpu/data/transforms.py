"""Host-side tokenization transforms (reference C6a/C6b).

The reference runs tokenize → random-crop → randomize → pad per sample in
DataLoader workers (reference data_processing.py:159-180). On TPU the host
is often a single weak core per chip, so this module does only the cheap,
string-shaped work — crop / tokenize / pad to a static length — vectorized
in numpy. The stochastic corruption (token randomization, annotation
masking) runs ON DEVICE inside the jitted train step (see
data/corruption.py), which the reference cannot do.

Semantics notes vs the reference:
- The reference crops the *tokenized* sequence (reference
  data_processing.py:64-83), so <sos>/<eos> can be cropped away. Here we
  crop the raw residues to seq_len-2 and then always add <sos>/<eos> —
  paper-faithful framing, and it gives the model a deterministic sentinel
  at both ends.
- Padding always uses <pad>=0. (The reference's per-sample ToTensor default
  would have padded with an out-of-vocab id had it ever padded — SURVEY
  ledger #10.)
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID, get_vocab


def random_crop(seq: str, max_residues: int, rng: np.random.Generator) -> str:
    """Uniform random window of `max_residues` (reference data_processing.py:64-83)."""
    if len(seq) <= max_residues:
        return seq
    start = int(rng.integers(0, len(seq) - max_residues + 1))
    return seq[start : start + max_residues]


def tokenize(seq: str, seq_len: int, rng: np.random.Generator | None = None) -> np.ndarray:
    """Crop → encode → add <sos>/<eos> → pad to `seq_len`. Returns (seq_len,) int32."""
    vocab = get_vocab()
    if rng is not None:
        seq = random_crop(seq, seq_len - 2, rng)
    else:
        seq = seq[: seq_len - 2]
    ids = vocab.encode(seq)
    out = np.full(seq_len, PAD_ID, dtype=np.int32)
    out[0] = SOS_ID
    out[1 : 1 + len(ids)] = ids
    out[1 + len(ids)] = EOS_ID
    return out


_NATIVE_MIN_BATCH = 8  # below this the ctypes call overhead wins


def tokenize_batch(
    seqs: Sequence[str],
    seq_len: int,
    rng: np.random.Generator | None = None,
    use_native: bool | None = None,
) -> np.ndarray:
    """Tokenize a list of sequences to a dense (B, seq_len) int32 batch.

    Real batches dispatch to the C++ kernel (native/tokenizer.cpp) when it
    is available — same output contract, parity-tested; pass
    use_native=False to force the numpy path. Crop windows are drawn from
    the path's own stream (both uniform, both seeded from `rng`), so the
    two paths are each reproducible but not window-identical.
    """
    if use_native is None:
        use_native = len(seqs) >= _NATIVE_MIN_BATCH
    if use_native:
        from proteinbert_tpu.native import tokenize_batch_native

        out = tokenize_batch_native(seqs, seq_len, rng)
        if out is not None:
            return out
    out = np.full((len(seqs), seq_len), PAD_ID, dtype=np.int32)
    for i, s in enumerate(seqs):
        out[i] = tokenize(s, seq_len, rng)
    return out
