"""Amino-acid vocabulary (reference C5).

Same token space as the reference `create_amino_acid_vocab`
(reference data_processing.py:337-348): the 22-char alphabet
'ACDEFGHIKLMNPQRSTUVWXY' (also re-declared at reference dummy_tests.py:16)
plus four specials. The reference builds it with torchtext and gets
<pad>=0, <sos>=1, <eos>=2, <unk>=3, then the AA chars at 4..25; we keep the
exact same ids (26 total) without the torchtext dependency, and expose a
numpy LUT-based encoder so tokenization is vectorizable (the reference
tokenizes one char at a time in a Python loop, data_processing.py:30-61).

Unknown characters map to <unk> (torchtext `set_default_index` parity,
reference data_processing.py:347).
"""

from __future__ import annotations

import functools

import numpy as np

ALPHABET = "ACDEFGHIKLMNPQRSTUVWXY"  # 22 chars, reference data_processing.py:338

PAD_ID = 0
SOS_ID = 1
EOS_ID = 2
UNK_ID = 3
N_SPECIAL = 4
SPECIALS = ("<pad>", "<sos>", "<eos>", "<unk>")

VOCAB_SIZE = N_SPECIAL + len(ALPHABET)  # 26


class Vocab:
    """Minimal char vocab with a 256-entry byte LUT for vectorized encode."""

    def __init__(self, alphabet: str = ALPHABET):
        self.alphabet = alphabet
        self.itos = list(SPECIALS) + list(alphabet)
        self.stoi = {s: i for i, s in enumerate(self.itos)}
        lut = np.full(256, UNK_ID, dtype=np.int32)
        for i, ch in enumerate(alphabet):
            lut[ord(ch)] = N_SPECIAL + i
            lut[ord(ch.lower())] = N_SPECIAL + i  # soft-masked FASTA residues
        self._lut = lut

    def __len__(self) -> int:
        return len(self.itos)

    def encode(self, seq: str) -> np.ndarray:
        """Encode an AA string to ids (no sos/eos added here)."""
        raw = np.frombuffer(seq.encode("ascii", errors="replace"), dtype=np.uint8)
        return self._lut[raw]

    def decode(self, ids) -> str:
        out = []
        for i in np.asarray(ids).tolist():
            tok = self.itos[i]
            out.append(tok if len(tok) == 1 else "")
        return "".join(out)

    @property
    def special_ids(self) -> np.ndarray:
        return np.arange(N_SPECIAL, dtype=np.int32)


@functools.lru_cache(maxsize=1)
def get_vocab() -> Vocab:
    return Vocab()
