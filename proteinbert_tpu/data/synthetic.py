"""Synthetic UniRef-like data (reference C15 fixture,
dummy_tests.py:23-38 parity): random AA strings + sparse annotations.

Used by the test suite, the `smoke` CLI command, and `pretrain` when no
--data file is given — the same role the reference's
`create_random_samples` plays for its smoke driver.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def make_random_proteins(
    n: int,
    rng: np.random.Generator,
    num_annotations: int = 512,
    max_len: int = 250,
    density: float = 0.005,
) -> Tuple[List[str], np.ndarray]:
    """n random AA strings of length 0..max_len and (n, A) sparse 0/1
    annotation rows (~`density` positive rate)."""
    from proteinbert_tpu.data.vocab import ALPHABET

    seqs = []
    for _ in range(n):
        L = int(rng.integers(0, max_len + 1))
        seqs.append("".join(rng.choice(list(ALPHABET), size=L)))
    ann = (rng.random((n, num_annotations)) < density).astype(np.float32)
    return seqs, ann


# Hydrophobic residues, used to derive LEARNABLE synthetic labels below.
_HYDROPHOBIC = set("AVILMFWC")


def make_task_batches(
    n: int,
    rng: np.random.Generator,
    kind: str,
    num_outputs: int,
    seq_len: int,
    batch_size: int,
):
    """Synthetic supervised batches whose labels are deterministic
    functions of the sequence — so a working fine-tune loop must drive the
    loss down (the role the reference's random-label smoke data cannot
    play). Labels:
      token_classification    — residue's token id mod num_outputs;
      sequence_classification — dominant-class of the per-residue labels;
      sequence_regression     — hydrophobic fraction of the sequence.
    Returns a list of {"tokens", "labels"} numpy batches.
    """
    from proteinbert_tpu.data.vocab import ALPHABET, PAD_ID
    from proteinbert_tpu.data.transforms import tokenize_batch

    seqs = []
    for _ in range(n):
        L = int(rng.integers(seq_len // 4, seq_len - 2))
        seqs.append("".join(rng.choice(list(ALPHABET), size=L)))
    tokens = tokenize_batch(seqs, seq_len)

    if kind == "token_classification":
        labels = (tokens % num_outputs).astype(np.int32)
    elif kind == "sequence_classification":
        per_tok = tokens % num_outputs
        labels = np.zeros(n, np.int32)
        for i in range(n):
            real = tokens[i] != PAD_ID
            labels[i] = np.bincount(per_tok[i][real],
                                    minlength=num_outputs).argmax()
    elif kind == "sequence_regression":
        labels = np.array(
            [sum(c in _HYDROPHOBIC for c in s) / max(len(s), 1) for s in seqs],
            np.float32,
        )
    else:
        raise ValueError(f"unknown task kind {kind!r}")

    from proteinbert_tpu.data.finetune_data import batch_task_data

    return batch_task_data(tokens, labels, batch_size)
