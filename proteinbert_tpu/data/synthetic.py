"""Synthetic UniRef-like data (reference C15 fixture,
dummy_tests.py:23-38 parity): random AA strings + sparse annotations.

Used by the test suite, the `smoke` CLI command, and `pretrain` when no
--data file is given — the same role the reference's
`create_random_samples` plays for its smoke driver.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def make_random_proteins(
    n: int,
    rng: np.random.Generator,
    num_annotations: int = 512,
    max_len: int = 250,
    density: float = 0.005,
) -> Tuple[List[str], np.ndarray]:
    """n random AA strings of length 0..max_len and (n, A) sparse 0/1
    annotation rows (~`density` positive rate)."""
    from proteinbert_tpu.data.vocab import ALPHABET

    seqs = []
    for _ in range(n):
        L = int(rng.integers(0, max_len + 1))
        seqs.append("".join(rng.choice(list(ALPHABET), size=L)))
    ann = (rng.random((n, num_annotations)) < density).astype(np.float32)
    return seqs, ann
