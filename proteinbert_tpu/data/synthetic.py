"""Synthetic UniRef-like data (reference C15 fixture,
dummy_tests.py:23-38 parity): random AA strings + sparse annotations.

Used by the test suite, the `smoke` CLI command, and `pretrain` when no
--data file is given — the same role the reference's
`create_random_samples` plays for its smoke driver.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def make_random_proteins(
    n: int,
    rng: np.random.Generator,
    num_annotations: int = 512,
    max_len: int = 250,
    density: float = 0.005,
) -> Tuple[List[str], np.ndarray]:
    """n random AA strings of length 0..max_len and (n, A) sparse 0/1
    annotation rows (~`density` positive rate)."""
    from proteinbert_tpu.data.vocab import ALPHABET

    seqs = []
    for _ in range(n):
        L = int(rng.integers(0, max_len + 1))
        seqs.append("".join(rng.choice(list(ALPHABET), size=L)))
    ann = (rng.random((n, num_annotations)) < density).astype(np.float32)
    return seqs, ann


# Hydrophobic residues, used to derive LEARNABLE synthetic labels below.
_HYDROPHOBIC = set("AVILMFWC")

# Two-state residue preferences for the STRUCTURED generator: state 0 is
# hydrophobic-core-like, state 1 polar/loop-like — a miniature of the
# secondary-structure signal ProteinBERT's real transfer tasks carry.
_STATE_RESIDUES = ("AVILMFWC", "DEKRHNQSTGP")


def make_structured_proteins(
    n: int,
    rng: np.random.Generator,
    num_annotations: int = 512,
    min_len: int = 40,
    max_len: int = 250,
    switch_prob: float = 0.05,
    fidelity: float = 0.70,
):
    """Synthetic proteins with LATENT STRUCTURE, for transfer experiments.

    Each sequence is emitted by a two-state Markov chain (persistence
    1 - `switch_prob`); a residue is drawn from its state's preferred
    set with prob `fidelity`, else uniformly. The defaults make a
    single residue a WEAK predictor of its own state (~75% decodable)
    while the surrounding segment is a strong one — so a frozen-trunk
    linear probe separates context-integrating features (what denoising
    pretraining learns) from random features (which can only surface
    per-token identity). Annotations
    are 3-mer occurrence bits (annotation j fires iff the j-th of
    `num_annotations` fixed 3-mers occurs), giving the global track a
    content-derived target. A denoising-pretrained trunk therefore
    learns exactly the local statistics that the downstream "predict
    the hidden state" task (see examples/transfer_experiment.py) needs —
    the synthetic miniature of the paper's secondary-structure
    transfer, which the reference only sketched in commented-out code
    (reference utils.py:348-493).

    Returns (seqs, annotations (n, A) float32, states: list of (L,)
    int8 arrays — the per-residue hidden state, usable as few-shot
    labels).
    """
    from proteinbert_tpu.data.vocab import ALPHABET

    alphabet = list(ALPHABET)
    # Fixed motif list drawn from the SAME rng: deterministic for a
    # seeded caller, shared between corpus and task splits.
    motifs = ["".join(rng.choice(alphabet, size=3))
              for _ in range(num_annotations)]
    motif_cols: dict = {}
    for j, m in enumerate(motifs):  # random 3-mers can collide
        motif_cols.setdefault(m, []).append(j)
    pools = [np.frombuffer(s.encode(), np.uint8) for s in _STATE_RESIDUES]
    alpha_arr = np.frombuffer("".join(alphabet).encode(), np.uint8)
    seqs = []
    states_out = []
    ann = np.zeros((n, num_annotations), np.float32)
    for i in range(n):
        L = int(rng.integers(min_len, max_len + 1))
        flips = rng.random(L) < switch_prob
        states = (np.cumsum(flips) + rng.integers(0, 2)) % 2
        faithful = rng.random(L) < fidelity
        # Vectorized residue draw (a per-char Python loop costs minutes
        # at the 16k-row rehearsal-corpus scale on a 1-core host).
        draw = np.where(states == 0,
                        pools[0][rng.integers(0, len(pools[0]), L)],
                        pools[1][rng.integers(0, len(pools[1]), L)])
        chars = np.where(faithful, draw,
                         alpha_arr[rng.integers(0, len(alpha_arr), L)])
        seq = chars.astype(np.uint8).tobytes().decode("ascii")
        seqs.append(seq)
        states_out.append(states.astype(np.int8))
        # O(L) motif membership via the sequence's own 3-mer set,
        # instead of O(L * num_annotations) substring scans.
        for m in {seq[k:k + 3] for k in range(L - 2)}:
            for j in motif_cols.get(m, ()):
                ann[i, j] = 1.0
    return seqs, ann, states_out


def make_task_batches(
    n: int,
    rng: np.random.Generator,
    kind: str,
    num_outputs: int,
    seq_len: int,
    batch_size: int,
):
    """Synthetic supervised batches whose labels are deterministic
    functions of the sequence — so a working fine-tune loop must drive the
    loss down (the role the reference's random-label smoke data cannot
    play). Labels:
      token_classification    — residue's token id mod num_outputs;
      sequence_classification — dominant-class of the per-residue labels;
      sequence_regression     — hydrophobic fraction of the sequence.
    Returns a list of {"tokens", "labels"} numpy batches.
    """
    from proteinbert_tpu.data.vocab import ALPHABET, PAD_ID
    from proteinbert_tpu.data.transforms import tokenize_batch

    seqs = []
    for _ in range(n):
        L = int(rng.integers(seq_len // 4, seq_len - 2))
        seqs.append("".join(rng.choice(list(ALPHABET), size=L)))
    tokens = tokenize_batch(seqs, seq_len)

    if kind == "token_classification":
        labels = (tokens % num_outputs).astype(np.int32)
    elif kind == "sequence_classification":
        per_tok = tokens % num_outputs
        labels = np.zeros(n, np.int32)
        for i in range(n):
            real = tokens[i] != PAD_ID
            labels[i] = np.bincount(per_tok[i][real],
                                    minlength=num_outputs).argmax()
    elif kind == "sequence_regression":
        labels = np.array(
            [sum(c in _HYDROPHOBIC for c in s) / max(len(s), 1) for s in seqs],
            np.float32,
        )
    else:
        raise ValueError(f"unknown task kind {kind!r}")

    from proteinbert_tpu.data.finetune_data import batch_task_data

    return batch_task_data(tokens, labels, batch_size)
