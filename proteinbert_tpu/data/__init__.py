from proteinbert_tpu.data.vocab import (
    ALPHABET,
    PAD_ID,
    SOS_ID,
    EOS_ID,
    UNK_ID,
    VOCAB_SIZE,
    N_SPECIAL,
    Vocab,
    get_vocab,
)
from proteinbert_tpu.data.transforms import (
    tokenize,
    tokenize_batch,
    random_crop,
    crop_starts,
    epoch_crop_seed,
    splitmix64,
)
from proteinbert_tpu.data.corruption import (
    randomize_tokens,
    corrupt_annotations,
    corrupt_batch,
    corrupt_packed_batch,
    packed_weights,
    pretrain_weights,
)
from proteinbert_tpu.data.dataset import (
    InMemoryPretrainingDataset,
    HDF5PretrainingDataset,
    make_bucketed_iterator,
    make_pretrain_iterator,
    Subset,
    train_eval_split,
)
from proteinbert_tpu.data.packing import (
    PackPlanner,
    make_packed_iterator,
    pack_rows,
    unpack_segments,
)

__all__ = [
    "ALPHABET", "PAD_ID", "SOS_ID", "EOS_ID", "UNK_ID", "VOCAB_SIZE",
    "N_SPECIAL", "Vocab", "get_vocab",
    "tokenize", "tokenize_batch", "random_crop",
    "crop_starts", "epoch_crop_seed", "splitmix64",
    "randomize_tokens", "corrupt_annotations", "corrupt_batch",
    "corrupt_packed_batch", "packed_weights", "pretrain_weights",
    "InMemoryPretrainingDataset", "HDF5PretrainingDataset",
    "make_bucketed_iterator", "make_pretrain_iterator",
    "Subset", "train_eval_split",
    "PackPlanner", "make_packed_iterator", "pack_rows", "unpack_segments",
]
