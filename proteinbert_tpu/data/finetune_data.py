"""Labeled fine-tuning datasets from TSV files (SURVEY C14).

The reference never defined a fine-tuning data format (its harness is
commented-out code, reference utils.py:348-493). Ours is a 2-column TSV,
`sequence<TAB>label`, one protein per line, `#` comments allowed:

  token_classification    label per residue: either a digit string as long
                          as the sequence ("01123...") or comma-separated
                          ints ("0,1,12,3"); positions that carry no label
                          (<sos>/<eos>/<pad>) are -1 in the batch and
                          masked out of the loss (train/finetune.task_loss).
  sequence_classification one int per line.
  sequence_regression     one float per line.

This covers the ProteinBERT paper's benchmark shapes (secondary
structure, remote homology, stability, fluorescence).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from proteinbert_tpu.data.transforms import tokenize_batch


def _parse_token_labels(raw: str, seq: str, lineno: int) -> List[int]:
    if "," in raw:
        labels = [int(x) for x in raw.split(",")]
    else:
        labels = [int(c) for c in raw]
    if len(labels) != len(seq):
        raise ValueError(
            f"line {lineno}: {len(labels)} labels for {len(seq)} residues"
        )
    return labels


def load_task_tsv(
    path: str, kind: str, seq_len: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (tokens (N, seq_len) int32, labels).

    labels: (N, seq_len) int32 with -1 at unlabeled positions for
    token_classification (aligned to the <sos>-shifted token layout);
    (N,) int32 for sequence_classification; (N,) float32 for regression.
    """
    seqs: List[str] = []
    raw_labels: List[str] = []
    linenos: List[int] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) != 2:
                raise ValueError(
                    f"line {lineno}: expected 'sequence<TAB>label', "
                    f"got {len(parts)} fields")
            seqs.append(parts[0])
            raw_labels.append(parts[1])
            linenos.append(lineno)

    tokens = tokenize_batch(seqs, seq_len)

    if kind == "token_classification":
        labels = np.full((len(seqs), seq_len), -1, np.int32)
        for i, (seq, raw) in enumerate(zip(seqs, raw_labels)):
            per_res = _parse_token_labels(raw, seq, linenos[i])
            # Residue j sits at token position j+1 (<sos> at 0); residues
            # beyond the crop window are dropped with their labels.
            n = min(len(per_res), seq_len - 2)
            labels[i, 1:1 + n] = per_res[:n]
        return tokens, labels
    if kind == "sequence_classification":
        return tokens, np.array([int(x) for x in raw_labels], np.int32)
    if kind == "sequence_regression":
        return tokens, np.array([float(x) for x in raw_labels], np.float32)
    raise ValueError(f"unknown task kind {kind!r}")


def batch_task_data(
    tokens: np.ndarray,
    labels: np.ndarray,
    batch_size: int,
    rng: np.random.Generator | None = None,
) -> List[Dict[str, np.ndarray]]:
    """Shuffle (if rng) and split into full batches (remainder dropped —
    static shapes keep every step on the same compiled program)."""
    n = len(tokens)
    order = rng.permutation(n) if rng is not None else np.arange(n)
    batches = []
    for i in range(0, n - batch_size + 1, batch_size):
        idx = order[i:i + batch_size]
        batches.append({"tokens": tokens[idx], "labels": labels[idx]})
    return batches
