"""Background batch prefetching (host↔device overlap).

The reference overlaps data loading with compute via torch DataLoader
worker processes (reference utils.py:99-105). The TPU-native equivalent
is simpler: the jitted step is dispatched asynchronously, so the host is
free during device compute — all that is needed is to hide the HOST cost
of producing the next batch (HDF5 reads, tokenization, numpy gathers)
behind the in-flight step. One daemon thread fills a small queue;
`prefetch()` wraps any batch iterator.

Exceptions raised by the source iterator are re-raised at the consuming
`next()` (not lost on the thread), and `close()` / generator GC stops the
thread promptly.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

_SENTINEL = object()


class PrefetchIterator:
    """Iterator view over `source` with `depth` batches produced ahead.

    Starvation accounting: `wait_s` accumulates the wall seconds the
    CONSUMER spent blocked on an empty queue (i.e. the host input
    pipeline failed to stay ahead of the device) and `batches` counts
    deliveries — the two numbers telemetry exports as the
    `data_wait_seconds` / `data_batches_total` metrics, turning "is the
    chip starving?" from a data-bench rerun into a per-run gauge."""

    def __init__(self, source: Iterator, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error = None
        self._done = False
        self._source = source
        self.wait_s = 0.0
        self.batches = 0
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        try:
            for item in self._source:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # re-raised on the consumer side
            self._error = e
        while not self._stop.is_set():
            try:
                self._q.put(_SENTINEL, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def _raise_pending_error(self):
        """Re-raise the producer's exception ON THE CONSUMER — with its
        ORIGINAL traceback (the exception object carries the producer
        frame's __traceback__, so the report points at the raising line
        inside the source iterator, not at this queue plumbing)."""
        err, self._error = self._error, None
        self._done = True
        raise err.with_traceback(err.__traceback__)

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                # The fill thread can only be gone after delivering the
                # sentinel OR after close(); either way nothing more is
                # coming — never block a training loop forever. A
                # producer that DIED on an exception must surface that
                # exception here, not a generic StopIteration that
                # reads as clean end-of-data.
                if self._stop.is_set() or not self._thread.is_alive():
                    if self._error is not None:
                        self.wait_s += time.perf_counter() - t0
                        self._raise_pending_error()
                    self._done = True
                    raise StopIteration from None
        self.wait_s += time.perf_counter() - t0
        if item is _SENTINEL:
            if self._error is not None:
                self._raise_pending_error()
            self._done = True
            raise StopIteration
        self.batches += 1
        return item

    def close(self):
        self._stop.set()

    def __del__(self):
        self.close()


def prefetch(source: Iterator, depth: int = 2) -> PrefetchIterator:
    """Wrap `source` so its batches are produced `depth` ahead on a
    background thread. depth=0 semantics (no-op) are the caller's choice —
    pass the source through unwrapped."""
    return PrefetchIterator(source, depth)
