"""Pretraining datasets + batch iterator (reference C7/C8, working version).

The reference ships two datasets: an in-memory DataFrame one (reference
data_processing.py:146-183) and an HDF5 one that is broken as committed —
it walks root datasets as groups, uses the removed h5py `.value` API, and
its `__len__`/`get_data` index per-file metadata instead of rows (reference
data_processing.py:186-333; SURVEY ledger #8). Both are rebuilt here:

- `InMemoryPretrainingDataset`: tokenizes a seqs+annotations table into
  dense numpy arrays once, up front; batches are two fancy-index gathers.
- `HDF5PretrainingDataset`: lazy reader over the HDF5 layout produced by
  `proteinbert_tpu.etl.h5_builder` (same dataset names the reference
  builder writes: `seqs`, `seq_lengths`, `annotation_masks`,
  `included_annotations`, `uniprot_ids` — reference uniref_dataset.py:
  238-245). Raw strings are cached per block; tokenization (with optional
  per-access random crop, matching reference data_processing.py:64-83)
  happens per batch.
- `make_pretrain_iterator`: shuffling, per-host sharded, infinite batch
  iterator yielding CLEAN {"tokens", "annotations"} numpy batches; the
  stochastic corruption happens on device (data/corruption.py). This
  replaces the reference's torch DataLoader factory (reference
  utils.py:71-107) — there is no worker pool to tune (and the reference's
  tuner never varied workers anyway, utils.py:61; SURVEY ledger #11).
  Shuffling is block-aware when the dataset declares a preferred block
  size, so HDF5 reads stay sequential-ish instead of one random block
  fetch per row.
"""

from __future__ import annotations

import collections
from typing import Dict, Iterator, Optional, Sequence

import numpy as np

from proteinbert_tpu.data.transforms import epoch_crop_seed, tokenize_batch


def _window_seed(crop_seed: Optional[int], epoch: int) -> Optional[int]:
    """Per-epoch window seed, or None when cropping is disabled."""
    if crop_seed is None:
        return None
    return epoch_crop_seed(crop_seed, epoch)


class InMemoryPretrainingDataset:
    """Dense in-RAM dataset (reference data_processing.py:146-183 parity).

    Args:
      seqs: list of AA strings.
      annotations: (N, A) 0/1 array (dense or castable).
      seq_len: static padded length.
      crop_seed: if given, sequences longer than seq_len-2 are re-cropped
        to a COUNTER-BASED window per epoch — the window is a pure
        function of (crop_seed, epoch, row index), so every epoch sees a
        fresh window (matching the reference's per-access stochastic
        crop, reference data_processing.py:64-83) yet a resumed run
        reproduces an uninterrupted one byte-for-byte (VERDICT r1 Weak
        #3: round 1's stateful crop_rng broke this). If None, long rows
        are head-truncated once and all rows are served from the dense
        pre-tokenized cache.
    """

    def __init__(
        self,
        seqs: Sequence[str],
        annotations: np.ndarray,
        seq_len: int,
        crop_seed: Optional[int] = None,
    ):
        annotations = np.asarray(annotations)
        if len(seqs) != len(annotations):
            raise ValueError(f"{len(seqs)} seqs vs {len(annotations)} annotation rows")
        self.seq_len = seq_len
        self.crop_seed = crop_seed
        self.tokens = tokenize_batch(seqs, seq_len)
        if crop_seed is not None:
            # Only long rows need per-access re-tokenization; short rows
            # always come from the dense cache, and only long rows' raw
            # strings are retained.
            self._long_seqs = {
                i: s for i, s in enumerate(seqs) if len(s) > seq_len - 2
            }
            self._long = np.zeros(len(seqs), dtype=bool)
            self._long[list(self._long_seqs)] = True
        else:
            self._long_seqs = None
            self._long = None
        self.annotations = annotations.astype(np.float32)

    def row_lengths(self) -> np.ndarray:
        """(N,) tokenized lengths incl. <sos>/<eos> (crop-invariant)."""
        return (self.tokens != 0).sum(axis=1).astype(np.int64)

    def __len__(self) -> int:
        return len(self.tokens)

    def __getitem__(self, i) -> Dict[str, np.ndarray]:
        """Epoch-0 view of row i — sugar for `get_row(i)`. Single-row and
        batched access share ONE code path (get_batch), so `ds[i]` equals
        `get_batch([i], epoch=0)` row 0 by construction (VERDICT r2 Weak
        #4: these paths used to re-implement each other and pinned
        different windows)."""
        return self.get_row(i)

    def get_row(self, i: int, epoch: int = 0) -> Dict[str, np.ndarray]:
        batch = self.get_batch(np.array([int(i)]), epoch=epoch)
        return {k: v[0] for k, v in batch.items()}

    def get_batch(self, idx: np.ndarray, epoch: int = 0) -> Dict[str, np.ndarray]:
        """Vectorized gather; long rows take their (epoch, row) window,
        re-tokenized in ONE batched call (not one call per row)."""
        tokens = self.tokens[idx]
        if self._long is not None:
            positions = np.flatnonzero(self._long[idx])
            if len(positions):
                ids = np.asarray(idx)[positions]
                tokens[positions] = tokenize_batch(
                    [self._long_seqs[int(i)] for i in ids], self.seq_len,
                    _window_seed(self.crop_seed, epoch), ids,
                )
        return {"tokens": tokens, "annotations": self.annotations[idx]}


class HDF5PretrainingDataset:
    """Working lazy HDF5 reader (fixes reference data_processing.py:186-333).

    Caches raw (decoded) sequence strings + annotation rows per block and
    tokenizes at access time; long rows take a counter-based crop window
    per (crop_seed, epoch, row) — fresh each epoch (the reference crops
    stochastically per access, data_processing.py:64-83), deterministic
    on resume. Use with the block-aware iterator: accesses grouped by
    block amortize one h5 read per `BLOCK` rows.
    """

    BLOCK = 1024

    def __init__(
        self,
        h5_path: str,
        seq_len: int,
        cache_blocks: int = 8,
        crop_seed: Optional[int] = None,
    ):
        import h5py  # local import: etl dep, not needed on TPU workers

        self._f = h5py.File(h5_path, "r")
        self.seq_len = seq_len
        self.crop_seed = crop_seed
        self._n = int(self._f["seq_lengths"].shape[0])
        self.num_annotations = int(self._f["annotation_masks"].shape[1])
        self._cache: "collections.OrderedDict[int, tuple]" = collections.OrderedDict()
        self._cache_blocks = cache_blocks

    def __len__(self) -> int:
        return self._n

    def row_lengths(self) -> np.ndarray:
        """(N,) tokenized lengths incl. <sos>/<eos>, capped at seq_len —
        stable across epochs even under re-cropping (a crop moves the
        window, not the length). Reads the h5 `seq_lengths` column the
        reference writes but never uses (reference uniref_dataset.py:245)."""
        raw = self._f["seq_lengths"][:].astype(np.int64)
        return np.minimum(raw + 2, self.seq_len)

    @property
    def shuffle_block(self) -> int:
        return self.BLOCK

    def _load_block(self, b: int):
        blk = self._cache.get(b)
        if blk is None:
            lo, hi = b * self.BLOCK, min((b + 1) * self.BLOCK, self._n)
            raw = self._f["seqs"][lo:hi]
            seqs = [s.decode() if isinstance(s, bytes) else str(s) for s in raw]
            ann = self._f["annotation_masks"][lo:hi].astype(np.float32)
            blk = (seqs, ann)
            self._cache[b] = blk
            if len(self._cache) > self._cache_blocks:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(b)
        return blk

    def __getitem__(self, i: int) -> Dict[str, np.ndarray]:
        """Epoch-0 view of row i — sugar for `get_row(i)`; one code path
        with get_batch (see InMemoryPretrainingDataset.__getitem__)."""
        if not 0 <= i < self._n:
            raise IndexError(i)
        return self.get_row(i)

    def get_row(self, i: int, epoch: int = 0) -> Dict[str, np.ndarray]:
        batch = self.get_batch(np.array([int(i)]), epoch=epoch)
        return {k: v[0] for k, v in batch.items()}

    def get_batch(self, idx: np.ndarray, epoch: int = 0) -> Dict[str, np.ndarray]:
        """Batch gather grouped by block so each block is read/decoded once."""
        order = np.argsort(idx // self.BLOCK, kind="stable")
        seqs_out: list = [None] * len(idx)
        ann_out: list = [None] * len(idx)
        for pos in order:
            i = int(idx[pos])
            seqs, ann = self._load_block(i // self.BLOCK)
            j = i % self.BLOCK
            seqs_out[pos] = seqs[j]
            ann_out[pos] = ann[j]
        return {
            "tokens": tokenize_batch(
                seqs_out, self.seq_len, _window_seed(self.crop_seed, epoch),
                np.asarray(idx, np.int64)),
            "annotations": np.stack(ann_out),
        }

    def close(self) -> None:
        self._f.close()


def _epoch_order(
    n: int, rng: np.random.Generator, shuffle: bool, block: Optional[int]
) -> np.ndarray:
    """Epoch permutation; block-shuffled (blocks permuted, rows permuted
    within each block) when the dataset prefers block-local access."""
    if not shuffle:
        return np.arange(n)
    if not block or block >= n:
        return rng.permutation(n)
    starts = rng.permutation(np.arange(0, n, block))
    out = np.empty(n, dtype=np.int64)
    pos = 0
    for s in starts:
        hi = min(s + block, n)
        chunk = np.arange(s, hi)
        rng.shuffle(chunk)
        out[pos : pos + len(chunk)] = chunk
        pos += len(chunk)
    return out


def _make_fetch(dataset):
    """(row-index array, epoch) → {"tokens","annotations"} batch, via the
    dataset's batched gather when it has one. The epoch is forwarded so
    crop windows can vary per epoch while staying a pure function of
    (crop_seed, epoch, row); third-party datasets whose get_batch lacks
    an epoch parameter are called without it."""
    get_batch = getattr(dataset, "get_batch", None)
    takes_epoch = False
    if get_batch is not None:
        import inspect

        try:
            params = inspect.signature(get_batch).parameters
            # **kwargs counts as epoch-capable: a wrapper that forwards
            # kwargs verbatim must still receive the epoch (ADVICE r2).
            takes_epoch = "epoch" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            takes_epoch = False

    def fetch(idx: np.ndarray, epoch: int = 0) -> Dict[str, np.ndarray]:
        if get_batch is not None:
            if takes_epoch:
                return get_batch(idx, epoch=epoch)
            return get_batch(idx)
        rows = [dataset[int(i)] for i in idx]
        return {
            "tokens": np.stack([r["tokens"] for r in rows]),
            "annotations": np.stack([r["annotations"] for r in rows]),
        }

    return fetch


def _check_per_host(n: int, batch_size: int, process_count: int) -> int:
    per_host = n // process_count
    if per_host < batch_size:
        raise ValueError(
            f"per-host shard of {per_host} rows (n={n}, hosts={process_count}) "
            f"cannot fill a batch of {batch_size}"
        )
    return per_host


def make_pretrain_iterator(
    dataset,
    batch_size: int,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    skip_batches: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or num_epochs-bounded) per-host sharded batch iterator.

    Each host sees a disjoint, EQUAL-SIZED slice of every epoch's
    permutation (the permutation is truncated to a multiple of
    process_count, so every host yields the same number of batches per
    epoch — unequal counts would deadlock multi-host collective steps at
    epoch boundaries). This is the per-host data feed the reference never
    had (SURVEY C18); the global batch is assembled on device via
    `jax.make_array_from_process_local_data`.

    Raises if the per-host shard can't fill one batch (a silent empty
    iterator would busy-loop forever in the num_epochs=None case).

    `skip_batches` fast-forwards past already-consumed batches on
    checkpoint resume WITHOUT loading their data — only the (cheap) epoch
    permutations are replayed, and because crop windows are a pure
    function of (crop_seed, epoch, row) the resumed run yields
    BYTE-IDENTICAL batches to an uninterrupted one (the reference resumes
    the iteration counter but replays data from scratch, reference
    utils.py:267-282; round 1 here replayed indices but not windows —
    closed per VERDICT r1 Weak #3).
    """
    n = len(dataset)
    per_host = _check_per_host(n, batch_size, process_count)
    block = getattr(dataset, "shuffle_block", None)
    fetch = _make_fetch(dataset)
    rng = np.random.default_rng(seed)
    epoch = 0
    while num_epochs is None or epoch < num_epochs:
        order = _epoch_order(n, rng, shuffle, block)[: per_host * process_count]
        # Contiguous split (not strided): keeps the block-local runs of
        # _epoch_order intact per host, so each HDF5 block is read by one
        # host (two at a shard boundary) instead of all of them.
        shard = order[process_index * per_host : (process_index + 1) * per_host]
        for lo in range(0, per_host - batch_size + 1, batch_size):
            if skip_batches > 0:
                skip_batches -= 1
                continue
            yield fetch(shard[lo : lo + batch_size], epoch)
        epoch += 1


def make_bucketed_iterator(
    dataset,
    batch_size: int,
    buckets: Sequence[int],
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    skip_batches: int = 0,
    metrics=None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Length-bucketed batch iterator (SURVEY §7 stage 10).

    The reference pads every sequence to one global max length (reference
    data_processing.py:155,165-167) — at seq_len 2048 with typical UniRef
    lengths (~350) that is >80% pad FLOPs. Here each row goes to the
    smallest bucket that fits its tokenized length and batches are emitted
    per bucket, sliced to the bucket length. Model + loss are
    shape-parametric in L (per-feature LN, weighted loss), so each bucket
    just compiles one more executable of the same jitted step.

    Multi-host lockstep: every host runs the SAME bucket bookkeeping over
    the full global index stream (identical seed → identical fill order),
    and when a bucket fills with batch_size·process_count rows each host
    fetches only its slice — so at every step all hosts present the same
    batch shape and per-epoch batch count, the invariant collective steps
    require (`batch_size` stays per-host, like make_pretrain_iterator).

    `skip_batches` replays only the (cheap) index bookkeeping — no data is
    fetched for skipped batches, so checkpoint resume costs seconds, not
    an I/O replay of the consumed stream.

    Buckets must be ascending; the last must equal the dataset seq_len
    (rows longer than it are cropped there by tokenization). Bucket
    remainders carry over epoch boundaries and are dropped only when the
    iterator ends (num_epochs reached) — with static batch shapes a
    partial batch cannot be emitted; the drop is COUNTED, not silent:
    with a `metrics` registry the iterator increments
    `data_dropped_rows_total{strategy="bucketed"}` at exhaustion and
    sets a per-batch `data_pad_fraction{strategy="bucketed"}` gauge —
    the SAME metric names the packed iterator reports
    (data/packing.make_packed_iterator), so `pbt diagnose` compares the
    two strategies from one stream.
    """
    if isinstance(buckets, str) or not hasattr(buckets, "__iter__"):
        raise ValueError(
            f"buckets must be a sequence of ints, got {buckets!r} "
            "(e.g. --set data.buckets=[512,1024,2048])")
    try:
        buckets = sorted(int(b) for b in buckets)
    except (TypeError, ValueError):
        raise ValueError(f"buckets must be ints, got {buckets!r}") from None
    if buckets[-1] != dataset.seq_len:
        raise ValueError(
            f"last bucket {buckets[-1]} must equal dataset seq_len "
            f"{dataset.seq_len}")
    lengths = dataset.row_lengths()
    n = len(dataset)
    per_host = _check_per_host(n, batch_size, process_count)
    global_batch = batch_size * process_count
    # Assign each row to its bucket once (lengths are crop-invariant).
    bucket_of = np.searchsorted(buckets, lengths)

    block = getattr(dataset, "shuffle_block", None)
    fetch = _make_fetch(dataset)
    rng = np.random.default_rng(seed)
    pending: Dict[int, list] = {b: [] for b in range(len(buckets))}
    pad_gauge = drop_counter = None
    if metrics is not None:
        pad_gauge = metrics.gauge("data_pad_fraction", strategy="bucketed")
        drop_counter = metrics.counter("data_dropped_rows_total",
                                       strategy="bucketed")
    epoch = 0
    while num_epochs is None or epoch < num_epochs:
        order = _epoch_order(n, rng, shuffle, block)[: per_host * process_count]
        for i in order:
            b = int(bucket_of[i])
            pending[b].append(i)
            if len(pending[b]) < global_batch:
                continue
            rows = pending[b]
            pending[b] = []
            if skip_batches > 0:
                skip_batches -= 1
                continue
            mine = np.asarray(
                rows[process_index * batch_size
                     : (process_index + 1) * batch_size])
            batch = fetch(mine, epoch)
            batch["tokens"] = batch["tokens"][:, : buckets[b]]
            if pad_gauge is not None:
                pad_gauge.set(float((batch["tokens"] == 0).mean()))
            yield batch
        epoch += 1
    # End of data: the sub-global-batch remainders in each bucket cannot
    # be emitted at a static shape — count them (every host sees the
    # same bookkeeping, so the count is host-consistent).
    dropped = sum(len(rows) for rows in pending.values())
    if dropped:
        if drop_counter is not None:
            drop_counter.inc(dropped)
        import logging

        logging.getLogger(__name__).warning(
            "bucketed iterator ended with %d pending rows across %d "
            "buckets (static batch shapes cannot emit partial batches); "
            "counted in data_dropped_rows_total", dropped,
            sum(1 for rows in pending.values() if rows))


class Subset:
    """Row-index view over a dataset — the train/test split primitive
    (reference C8's create_pretrain_dataloaders random_split, reference
    utils.py:71-107). Proxies the iterator-facing surface (get_batch,
    row_lengths, seq_len, shuffle_block) onto the parent."""

    def __init__(self, dataset, indices: np.ndarray):
        self._ds = dataset
        self._idx = np.asarray(indices, dtype=np.int64)
        self.seq_len = dataset.seq_len
        self._fetch = _make_fetch(dataset)

    def __len__(self) -> int:
        return len(self._idx)

    def __getitem__(self, i: int):
        return self.get_row(i)

    def get_row(self, i: int, epoch: int = 0):
        batch = self.get_batch(np.array([int(i)]), epoch=epoch)
        return {k: v[0] for k, v in batch.items()}

    def get_batch(self, idx: np.ndarray, epoch: int = 0):
        # Parent row ids key the crop windows, so a row's window is the
        # same whether accessed through the view or the parent.
        return self._fetch(self._idx[np.asarray(idx)], epoch)

    def row_lengths(self) -> np.ndarray:
        return self._ds.row_lengths()[self._idx]

    @property
    def shuffle_block(self):
        # When the view's indices are sorted (train_eval_split sorts its
        # slices), consecutive view positions map to nearby parent rows,
        # so the parent's block-local access pattern survives the
        # indirection approximately; unsorted views lose it.
        if np.all(np.diff(self._idx) > 0):
            return getattr(self._ds, "shuffle_block", None)
        return None


def train_eval_split(dataset, eval_frac: float, seed: int = 0):
    """(train_view, eval_view) with a deterministic shuffled split
    (reference random_split parity, reference utils.py:93-97)."""
    if not 0.0 < eval_frac < 1.0:
        raise ValueError(f"eval_frac must be in (0, 1), got {eval_frac}")
    n = len(dataset)
    order = np.random.default_rng(seed).permutation(n)
    n_eval = max(1, int(n * eval_frac))
    # Sorted slices: the split stays random (membership came from the
    # permutation) while each view walks its parent monotonically, which
    # preserves HDF5 block locality (see Subset.shuffle_block).
    return (Subset(dataset, np.sort(order[n_eval:])),
            Subset(dataset, np.sort(order[:n_eval])))
