"""On-device denoising corruption ops (reference C6c/C6d, re-designed for TPU).

The reference applies stochastic corruption per sample on the host inside
DataLoader workers (`SimpleTokenRandomizer` reference data_processing.py:
86-105, `AnnotationMasking` reference data_processing.py:108-142). On TPU the
host core is the bottleneck, so here corruption is a pure jittable function
of a JAX PRNG key that runs fused into the train step on device — the host
feeds *clean* tokens/annotations, the device derives (X, Y, weights).

Semantics (paper-corrected per SURVEY ledger):
- Token randomization: each non-special position is replaced w.p. `p` by a
  token drawn uniformly from the 22 real AA tokens (ids 4..25). Special
  positions (<pad>/<sos>/<eos>) are never touched (reference
  data_processing.py:95-104).
- Annotation corruption: per protein, w.p. `corrupt_prob` the annotation
  vector is kept but noised (positives dropped w.p. `drop_prob`, negatives
  flipped on w.p. `add_prob`); otherwise the entire vector is hidden
  (all zeros) — the reference's p=0.5 hide-all branch kept as an explicit,
  configurable denoising design (reference data_processing.py:127-128,
  SURVEY ledger #5).
- Loss weights: per-token weight = non-pad mask of the *clean* sequence;
  per-annotation weight = 1 iff the protein has any positive annotation,
  broadcast over the annotation dim (reference data_processing.py:175-176).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from proteinbert_tpu.data.vocab import N_SPECIAL, PAD_ID, VOCAB_SIZE


def randomize_tokens(key: jax.Array, tokens: jax.Array, prob: float) -> jax.Array:
    """Randomly replace non-special tokens with random AA tokens.

    Args:
      key: PRNG key.
      tokens: (..., L) int32 clean token ids.
      prob: replacement probability (reference default 0.05,
        data_processing.py:90).
    Returns:
      (..., L) int32 corrupted tokens.
    """
    k_mask, k_draw = jax.random.split(key)
    replace = jax.random.bernoulli(k_mask, prob, tokens.shape)
    replace = jnp.logical_and(replace, tokens >= N_SPECIAL)
    random_aa = jax.random.randint(
        k_draw, tokens.shape, N_SPECIAL, VOCAB_SIZE, dtype=tokens.dtype
    )
    return jnp.where(replace, random_aa, tokens)


def corrupt_annotations(
    key: jax.Array,
    annotations: jax.Array,
    corrupt_prob: float,
    drop_prob: float,
    add_prob: float,
) -> jax.Array:
    """Noise-or-hide the (B, A) float annotation matrix (see module docstring)."""
    k_keep, k_drop, k_add = jax.random.split(key, 3)
    batch_shape = annotations.shape[:-1]
    keep = jax.random.bernoulli(k_keep, corrupt_prob, batch_shape)[..., None]
    dropped = jnp.where(
        jax.random.bernoulli(k_drop, drop_prob, annotations.shape),
        jnp.zeros_like(annotations),
        annotations,
    )
    added = jnp.where(
        jax.random.bernoulli(k_add, add_prob, annotations.shape),
        jnp.ones_like(annotations),
        dropped,
    )
    return jnp.where(keep, added, jnp.zeros_like(annotations))


def pretrain_weights(
    tokens: jax.Array, annotations: jax.Array
) -> Dict[str, jax.Array]:
    """Loss weights from the CLEAN batch (reference data_processing.py:175-176)."""
    seq_w = (tokens != PAD_ID).astype(jnp.float32)
    has_any = (annotations.sum(axis=-1, keepdims=True) > 0).astype(jnp.float32)
    ann_w = jnp.broadcast_to(has_any, annotations.shape)
    return {"local": seq_w, "global": ann_w}


def packed_weights(
    tokens: jax.Array, segment_ids: jax.Array, annotations: jax.Array
) -> Dict[str, jax.Array]:
    """Loss weights for a PACKED clean batch (data/packing.py layout).

    local: (B, L) — 1 at real (segment > 0) positions, like the unpacked
      non-pad mask (pad and real positions coincide: packed rows carry
      no interior padding).
    global: (B, S, A) — 1 iff the segment EXISTS in the row and has any
      positive annotation (the per-protein contract of
      `pretrain_weights`, applied per segment).
    """
    del tokens  # the segment map is the authoritative pad mask
    seq_w = (segment_ids > 0).astype(jnp.float32)
    S = annotations.shape[-2]
    seg_exists = (
        segment_ids[..., None] == jnp.arange(1, S + 1, dtype=segment_ids.dtype)
    ).any(axis=-2)  # (B, S)
    has_any = (annotations.sum(axis=-1) > 0) & seg_exists
    ann_w = jnp.broadcast_to(
        has_any[..., None].astype(jnp.float32), annotations.shape)
    return {"local": seq_w, "global": ann_w}


def corrupt_packed_batch(
    key: jax.Array,
    tokens: jax.Array,
    segment_ids: jax.Array,
    annotations: jax.Array,
    token_randomize_prob: float = 0.05,
    annotation_corrupt_prob: float = 0.5,
    annotation_drop_prob: float = 0.25,
    annotation_add_prob: float = 1e-4,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array], Dict[str, jax.Array]]:
    """`corrupt_batch` for PACKED rows (tokens (B, L), segment_ids
    (B, L), annotations (B, S, A) — data/packing.py).

    Segment-awareness comes for free from the existing primitives:
    `randomize_tokens` protects special positions BY TOKEN ID, so every
    packed sequence's <sos>/<eos>/<pad> stay untouched wherever they
    sit in the row; `corrupt_annotations` draws its keep/hide decision
    per leading-batch element, which on a (B, S, A) input is per
    SEGMENT — each packed protein independently keeps-and-noises or
    hides its annotation vector, exactly like an unpacked row would.
    """
    k_tok, k_ann = jax.random.split(key)
    x_local = randomize_tokens(k_tok, tokens, token_randomize_prob)
    x_global = corrupt_annotations(
        k_ann, annotations, annotation_corrupt_prob,
        annotation_drop_prob, annotation_add_prob,
    )
    X = {"local": x_local, "global": x_global}
    Y = {"local": tokens, "global": annotations}
    W = packed_weights(tokens, segment_ids, annotations)
    return X, Y, W


def corrupt_batch(
    key: jax.Array,
    tokens: jax.Array,
    annotations: jax.Array,
    token_randomize_prob: float = 0.05,
    annotation_corrupt_prob: float = 0.5,
    annotation_drop_prob: float = 0.25,
    annotation_add_prob: float = 1e-4,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array], Dict[str, jax.Array]]:
    """Derive the full (X, Y, weights) pretraining triple on device.

    Mirrors the reference Dataset __getitem__ contract (reference
    data_processing.py:159-180): X = corrupted inputs, Y = clean targets,
    weights = loss masks; each a {"local", "global"} dict.
    """
    k_tok, k_ann = jax.random.split(key)
    x_local = randomize_tokens(k_tok, tokens, token_randomize_prob)
    x_global = corrupt_annotations(
        k_ann, annotations, annotation_corrupt_prob,
        annotation_drop_prob, annotation_add_prob,
    )
    X = {"local": x_local, "global": x_global}
    Y = {"local": tokens, "global": annotations}
    W = pretrain_weights(tokens, annotations)
    return X, Y, W
