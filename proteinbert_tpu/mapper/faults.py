"""Env-spec fault hooks for the map engine (ISSUE 14).

The chaos drill (tools/map_drill.py) runs `pbt map` as a real
subprocess and needs deterministic injection points INSIDE it: SIGKILL
between the object write and the cursor advance, transient dispatch
failures with a retry count, a NaN poked into a block's output, and an
extra per-block latency to widen kill windows. Those points are
described by one spec string in the PBT_MAP_FAULTS environment
variable; the engine parses it here and consults the resulting
`MapFaults` at each hook point. An empty/absent spec is inert — the
production path pays a None-ish check only.

Spec format (semicolon-separated directives; shard/block are ints):

  crash=<shard>:<block>:<point>   SIGKILL self when the engine reaches
                                  `point` for that (shard, block).
                                  Points: block_fetched (ISSUE 19 —
                                  device results fetched to host but
                                  nothing written yet: the pipelined
                                  device-complete-but-uncommitted
                                  window, fired by engine.py),
                                  before_object, after_object,
                                  cursor_serialized, cursor_tmp_written,
                                  cursor_prev_updated, cursor_renamed
                                  (store.commit_block / ShardCursor).
  fail=<shard>:<block>:<times>    raise TransientDispatchError on the
                                  first <times> dispatch attempts of
                                  that block (then succeed).
  nan=<shard>:<block>             corrupt that block's output with a
                                  non-finite value (NaN-halt drill).
  latency=<seconds>               sleep this long before every block.

The drill-side builder for this format lives in tools/faults.py (the
shared injection surface of the fleet and map drills); this module is
the consumer and must stay importable from the package alone.
"""

from __future__ import annotations

import logging
import os
import signal
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

FAULT_ENV = "PBT_MAP_FAULTS"

CRASH_POINTS = ("block_fetched", "before_object", "after_object",
                "cursor_serialized", "cursor_tmp_written",
                "cursor_prev_updated", "cursor_renamed")


class TransientDispatchError(RuntimeError):
    """A dispatch attempt failed in a way worth retrying (injected by
    the drill; real transient backend errors may be wrapped into this
    by callers that can classify them)."""


class MapFaults:
    """Parsed PBT_MAP_FAULTS spec; every accessor is a no-op default."""

    def __init__(self,
                 crash: Optional[Dict[Tuple[int, int], str]] = None,
                 fail: Optional[Dict[Tuple[int, int], int]] = None,
                 nan: Optional[set] = None,
                 latency_s: float = 0.0):
        self._crash = dict(crash or {})
        self._fail = dict(fail or {})
        self._nan = set(nan or ())
        self.latency_s = float(latency_s)

    @classmethod
    def from_env(cls, env_var: str = FAULT_ENV) -> "MapFaults":
        return cls.parse(os.environ.get(env_var, ""))

    @classmethod
    def parse(cls, spec: str) -> "MapFaults":
        """Parse one spec string; malformed directives raise ValueError
        (a drill typo must fail loudly, not silently not-inject)."""
        crash: Dict[Tuple[int, int], str] = {}
        fail: Dict[Tuple[int, int], int] = {}
        nan: set = set()
        latency = 0.0
        for raw in (spec or "").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if "=" not in raw:
                raise ValueError(f"fault directive without '=': {raw!r}")
            kind, _, val = raw.partition("=")
            parts = val.split(":")
            if kind == "crash":
                if len(parts) != 3 or parts[2] not in CRASH_POINTS:
                    raise ValueError(
                        f"crash wants shard:block:point with point in "
                        f"{CRASH_POINTS}, got {val!r}")
                crash[(int(parts[0]), int(parts[1]))] = parts[2]
            elif kind == "fail":
                if len(parts) != 3:
                    raise ValueError(f"fail wants shard:block:times, "
                                     f"got {val!r}")
                fail[(int(parts[0]), int(parts[1]))] = int(parts[2])
            elif kind == "nan":
                if len(parts) != 2:
                    raise ValueError(f"nan wants shard:block, got {val!r}")
                nan.add((int(parts[0]), int(parts[1])))
            elif kind == "latency":
                latency = float(val)
            else:
                raise ValueError(f"unknown fault directive {kind!r}")
        return cls(crash=crash, fail=fail, nan=nan, latency_s=latency)

    def crash_hook(self, shard: int, block: int):
        """A callable(point) for store.commit_block: SIGKILL self at the
        armed point — the hardest landing a writer can take, exactly
        between two filesystem operations. Returns None when nothing is
        armed for this (shard, block), so the store pays no closure."""
        point = self._crash.get((int(shard), int(block)))
        if point is None:
            return None

        def hook(reached: str) -> None:
            if reached == point:
                logger.warning("FAULT INJECTION: SIGKILL at %s for shard "
                               "%d block %d", point, shard, block)
                os.kill(os.getpid(), signal.SIGKILL)

        return hook

    def take_failure(self, shard: int, block: int) -> bool:
        """Consume one injected dispatch failure for (shard, block);
        True while any remain."""
        key = (int(shard), int(block))
        left = self._fail.get(key, 0)
        if left <= 0:
            return False
        self._fail[key] = left - 1
        return True

    def poison_output(self, shard: int, block: int) -> bool:
        return (int(shard), int(block)) in self._nan

    def block_latency(self) -> None:
        if self.latency_s > 0:
            time.sleep(self.latency_s)

    def armed(self) -> bool:
        return bool(self._crash or self._fail or self._nan
                    or self.latency_s > 0)
