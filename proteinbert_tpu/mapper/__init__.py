"""Resumable sharded batch inference + integrity-verified embedding
store (`pbt map`, ISSUE 14).

Layout:
- `store.py`  — content-addressed block store, crash-safe shard
  cursors, quarantine sidecars, `verify_store` (stdlib+numpy only).
- `engine.py` — the map run loop: packed-trunk embedding, retries,
  poison quarantine, NaN halt, telemetry (imports jax — loaded lazily
  so `pbt map --verify` and `pbt diagnose --map` work on machines that
  only hold the artifacts).
- `faults.py` — the PBT_MAP_FAULTS injection hooks the chaos drill
  (tools/map_drill.py) drives.

docs/mapping.md is the operator reference.
"""

from proteinbert_tpu.mapper.faults import (  # noqa: F401
    FAULT_ENV, MapFaults, TransientDispatchError,
)
from proteinbert_tpu.mapper.store import (  # noqa: F401
    BlockFormatError, BlockIntegrityError, CursorError, EmbeddingStore,
    ShardCursor, StoreConfigError, StoreError, block_digest,
    commit_block, corpus_digest, deserialize_block, iter_embeddings,
    next_offset, resume_shard, serialize_block, shard_ranges,
    store_digests, verify_store,
)

__all__ = [
    "FAULT_ENV", "MapFaults", "TransientDispatchError",
    "BlockFormatError", "BlockIntegrityError", "CursorError",
    "EmbeddingStore", "ShardCursor", "StoreConfigError", "StoreError",
    "block_digest", "commit_block", "corpus_digest", "deserialize_block",
    "iter_embeddings", "next_offset", "resume_shard", "serialize_block",
    "shard_ranges", "store_digests", "verify_store",
    # lazy (jax-importing) engine surface:
    "run_map", "poison_reason",
]


def __getattr__(name):  # PEP 562: keep --verify jax-free
    if name in ("run_map", "poison_reason"):
        from proteinbert_tpu.mapper import engine

        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
