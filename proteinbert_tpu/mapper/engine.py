"""Resumable sharded batch-inference engine — the `pbt map` tentpole
(ISSUE 14, ROADMAP item 4).

Streams a corpus of sequences through the ragged packed trunk (the
PR 8 serving representation: heterogeneous sequences first-fit-packed
into fixed-shape rows, one warm executable for the whole run) and
writes a content-addressed embedding store (mapper/store.py). The run
is a set of DETERMINISTIC input shards (contiguous corpus ranges);
each shard advances block by block, and a block only enters the
shard's cursor after its payload is durably on disk — so SIGKILL at
any point resumes with at most one in-flight block of re-work per
shard and never drops or duplicates a sequence.

Failure containment, per the fleet layer's playbook (PR 10):

- **Transient dispatch errors** (TransientDispatchError) retry with
  capped exponential backoff under a retry budget (floor + ratio ×
  blocks); exhaustion fails the SHARD (typed), not the run.
- **Poisoned inputs** (non-string / empty / control characters) are
  quarantined to a per-shard sidecar with a typed reason and recorded
  in the block's cursor entry; the block proceeds without them.
- **Non-finite embeddings** halt the shard with a flight-recorder
  dump — numerical corruption must never be silently served.
- **SIGTERM/SIGINT** finish the in-flight block, flush the cursor, and
  exit preempted (exit 75 at the CLI, like pretrain) for a supervisor
  requeue.

Observability: schema-versioned map_start / map_shard / map_block /
map_end events, progress/throughput/re-work gauges and counters, and
`pbt diagnose --map` (obs/diagnose.py). docs/mapping.md is the
operator reference.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from proteinbert_tpu.mapper.faults import MapFaults, TransientDispatchError
from proteinbert_tpu.mapper.store import (
    EmbeddingStore, ShardCursor, block_digest, commit_block,
    corpus_digest, next_offset, resume_shard, serialize_block,
    shard_ranges,
)
from proteinbert_tpu.obs import as_telemetry

logger = logging.getLogger(__name__)

POISON_REASONS = ("non_string", "empty", "invalid_char")


# Typed map-run failures are reported through the run OUTCOME —
# "halted"/"error" on the map_end record — never as exceptions; the
# once-exported MapError/ShardHaltedError hierarchy was dead API and
# was removed by the ISSUE 15 dead-export sweep.


def poison_reason(seq: Any) -> Optional[str]:
    """Typed quarantine classification for one corpus record. Sequences
    merely longer than the model window are NOT poison — they truncate
    and count, same as every other inference surface."""
    if not isinstance(seq, str):
        return "non_string"
    if not seq:
        return "empty"
    if any(not (33 <= ord(c) <= 126) for c in seq):
        return "invalid_char"
    return None


def _embed_block_submit(params, cfg, ids: Sequence[str],
                        seqs: Sequence[str], rows_per_batch: int,
                        max_segments: int, buckets: Sequence[int]):
    """Submit one block through the ragged packed trunk and return a
    `fetch()` closure for its host-side materialization (ISSUE 19 —
    pipelined dispatch).

    First-fit-packs the block's sequences into (rows_per_batch,
    seq_len) rows and ENQUEUES `inference._packed_encode_batch` per
    fixed-shape batch (ONE warm executable for the whole run). JAX
    dispatch is async: this returns as soon as every chunk is enqueued,
    holding unmaterialized device arrays — the device computes while
    the caller does other host work. `fetch()` performs the blocking
    device→host transfers and scatters the per-segment outputs back to
    corpus order, returning the same arrays dict `_embed_block` always
    produced.

    Spans follow the ragged SERVING rule (serve/dispatch.
    RaggedDispatcher): each sequence occupies its bucket-quantized span
    with segment_ids covering the WHOLE span — that quantization is
    what makes the store's numbers match `pbt embed`/the serving
    surfaces within the documented jitted ≤1e-5 tolerance instead of
    being a third numerics regime (tests/test_mapper.py proves the
    parity). Deterministic in its inputs — the property the
    byte-identical-store contract rides on; submit/fetch split or not,
    the numbers are the same device computation."""
    import jax.numpy as jnp

    from proteinbert_tpu import inference
    from proteinbert_tpu.data.packing import OnlinePacker
    from proteinbert_tpu.data.vocab import PAD_ID

    seq_len = cfg.data.seq_len
    buckets = np.asarray(buckets)
    tokens = inference._tokenize_masked(list(seqs), seq_len,
                                        on_overflow="count")
    lengths = (tokens != PAD_ID).sum(axis=1).astype(np.int32)
    spans = buckets[np.searchsorted(buckets, lengths)]
    packer = OnlinePacker(seq_len, max_segments)
    for i, span in enumerate(spans):
        packer.place(i, int(span))
    rows = packer.pop_rows(len(packer))

    n = len(seqs)
    A = cfg.model.num_annotations
    pending = []
    for chunk_start in range(0, len(rows), rows_per_batch):
        chunk = rows[chunk_start:chunk_start + rows_per_batch]
        tok = np.zeros((rows_per_batch, seq_len), np.int32)
        seg = np.zeros((rows_per_batch, seq_len), np.int32)
        ann = np.zeros((rows_per_batch, max_segments, A), np.float32)
        for r, row in enumerate(chunk):
            for s, (pos, start, span) in enumerate(row):
                tok[r, start:start + span] = tokens[pos, :span]
                seg[r, start:start + span] = s + 1
        res = inference._packed_encode_batch(
            params, jnp.asarray(tok), jnp.asarray(seg),
            jnp.asarray(ann), cfg.model)
        pending.append((chunk, res))

    def fetch() -> Dict[str, Any]:
        out_global = out_local = None
        for chunk, res in pending:
            g = np.asarray(res["global"])
            lm = np.asarray(res["local_mean"])
            if out_global is None:
                out_global = np.zeros((n, g.shape[-1]), np.float32)
                out_local = np.zeros((n, lm.shape[-1]), np.float32)
            for r, row in enumerate(chunk):
                for s, (pos, _start, _span) in enumerate(row):
                    out_global[pos] = g[r, s]
                    out_local[pos] = lm[r, s]
        if out_global is None:  # every record was quarantined
            out_global = np.zeros((0, 1), np.float32)
            out_local = np.zeros((0, 1), np.float32)
        # Explicit UTF-8: np.array(dtype="S") on str raises for
        # non-ASCII ids (any real-world FASTA header can carry one),
        # and an id must never be able to kill a run — bytes round-trip
        # losslessly through iter_embeddings' .decode().
        return {"ids": np.array([str(i).encode("utf-8") for i in ids]),
                "lengths": lengths, "global": out_global,
                "local_mean": out_local}

    return fetch


def _embed_block(params, cfg, ids: Sequence[str], seqs: Sequence[str],
                 rows_per_batch: int, max_segments: int,
                 buckets: Sequence[int]) -> Dict[str, Any]:
    """One block, synchronously: submit + immediate fetch (the
    pre-pipeline entry, kept for parity tests and in-process callers)."""
    return _embed_block_submit(params, cfg, ids, seqs, rows_per_batch,
                               max_segments, buckets)()


def run_map(
    params, cfg, ids: Sequence[str], seqs: Sequence[str], store_dir: str,
    *,
    num_shards: int = 1,
    block_size: int = 64,
    rows_per_batch: int = 8,
    max_segments: int = 8,
    buckets: Optional[Sequence[int]] = None,
    telemetry=None,
    faults: Optional[MapFaults] = None,
    retry_limit: int = 3,
    retry_budget_floor: int = 4,
    retry_budget_ratio: float = 0.25,
    backoff_base_s: float = 0.05,
    backoff_cap_s: float = 2.0,
    max_blocks: Optional[int] = None,
    stop_flag=None,
    pipeline: bool = True,
) -> Dict[str, Any]:
    """Map the corpus into `store_dir`; resumes automatically from the
    shard cursors it finds there. Returns a stats dict whose "outcome"
    is one of obs.events.MAP_OUTCOMES ("completed" | "preempted" |
    "halted" | "error"). `max_blocks` bounds the blocks processed THIS
    invocation (outcome "preempted" when work remains — the smoke/test
    resume seam). `stop_flag` (callable → bool) replaces the default
    SIGTERM/SIGINT GracefulShutdown for in-process callers.

    `pipeline` (ISSUE 19) keeps ONE block in flight: block N+1's device
    compute is submitted before block N's host fetch + `commit_block`
    (object write, fsync, cursor advance), so the device stays fed
    through the durability I/O. Commit ORDER is strictly preserved —
    the cursor remains the commit point and never advances past an
    unfetched block, so the crash-window taxonomy and the
    byte-identical-resume contract (tools/map_drill.py) are unchanged;
    the new `block_fetched` crash point covers the device-complete-but-
    uncommitted window the split adds. False restores strictly serial
    compute → fetch → commit per block."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if rows_per_batch < 1:
        raise ValueError(f"rows_per_batch must be >= 1, got "
                         f"{rows_per_batch}")
    if len(ids) != len(seqs):
        raise ValueError(f"{len(ids)} ids != {len(seqs)} sequences")
    if not seqs:
        raise ValueError("no sequences given")
    from proteinbert_tpu.heads import trunk_fingerprint
    from proteinbert_tpu.serve.dispatch import resolve_buckets

    # The span-quantization ladder (serving semantics: cfg.data.buckets
    # unless overridden, else the single full-length bucket). It shapes
    # the packed rows and therefore the store BYTES, so it is pinned in
    # the manifest — a resume with a different ladder is a typed error,
    # not a silently mixed store.
    buckets = resolve_buckets(cfg, buckets)
    tele = as_telemetry(telemetry)
    if faults is None:
        faults = MapFaults.from_env()
    if faults.armed():
        logger.warning("FAULT INJECTION ACTIVE: map faults armed "
                       "(PBT_MAP_FAULTS)")
    store = EmbeddingStore(store_dir)
    fingerprint = trunk_fingerprint(params)
    manifest = store.ensure_manifest({
        "kind": "embedding_store",
        "corpus_n": len(seqs),
        "corpus_digest": corpus_digest(ids, seqs),
        "model_fingerprint": fingerprint,
        "num_shards": int(num_shards),
        "block_size": int(block_size),
        "rows_per_batch": int(rows_per_batch),
        "max_segments": int(max_segments),
        "seq_len": int(cfg.data.seq_len),
        "buckets": [int(b) for b in buckets],
    })
    ranges = shard_ranges(len(seqs), num_shards)

    config_rec = {k: manifest[k] for k in
                  ("corpus_n", "num_shards", "block_size",
                   "rows_per_batch", "max_segments", "seq_len",
                   "buckets")}
    config_rec["store"] = store.directory
    config_rec["model_fingerprint"] = fingerprint[:16]
    tele.emit("map_start", config=config_rec, pid=os.getpid())

    # Per-shard runtime state.
    shards: List[Dict[str, Any]] = []
    for shard, (lo, hi) in enumerate(ranges):
        state, info = resume_shard(store, shard)
        cursor = ShardCursor(store_dir, shard)
        nxt = next_offset(state)
        # Re-work this resume will incur: a dropped tail object is one
        # block; a torn-main-cursor fallback to `.prev` is one more IF
        # the lost generation recorded an advance (nxt < size — when it
        # only recorded the done-marker, nothing recomputes). Keeping
        # this exact makes map_end stats agree with the re-work that
        # `pbt diagnose --map` counts from repeated map_block rows.
        rework = int(info["tail_dropped"] is not None)
        if info["source"] == "prev" and not state["done"] \
                and nxt < hi - lo:
            rework += 1
        st = {"shard": shard, "lo": lo, "hi": hi, "state": state,
              "cursor": cursor, "next": nxt, "halted": False,
              "failed": False, "tail_dropped": info["tail_dropped"],
              "rework": rework,
              # Optimistic submit-side counters (ISSUE 19): where the
              # NEXT submit starts, ahead of the committed `next` /
              # `state["blocks"]` by at most the one in-flight block.
              # Single-threaded — only the run_map driver touches them.
              "pending_next": nxt,
              "pending_blocks": len(state["blocks"])}
        shards.append(st)
        is_resume = info["source"] != "fresh" or nxt > 0
        if state["done"]:
            continue
        if not is_resume:
            # Persist the empty generation so the very first advance
            # already has a `.prev` to fall back to.
            st["state"] = cursor.write_state(state)
        tele.emit("map_shard", shard=shard,
                  state="resume" if is_resume else "start",
                  next=nxt, size=hi - lo,
                  blocks=len(state["blocks"]),
                  cursor_source=info["source"],
                  tail_reworked=bool(info["tail_dropped"]))
        if st["rework"]:
            tele.metrics.counter("map_rework_blocks_total").inc(
                st["rework"])
        if nxt >= hi - lo:
            # Fully consumed but the done marker was lost (e.g. a torn
            # cursor fell back to the generation just before mark-done):
            # re-mark, never append a degenerate empty block.
            st["state"] = cursor.write_state(dict(st["state"], done=True))
            tele.emit("map_shard", shard=shard, state="done",
                      blocks=len(st["state"]["blocks"]))

    total_blocks = sum(
        (hi - lo + block_size - 1) // block_size for lo, hi in ranges)
    budget = [max(retry_budget_floor,
                  int(retry_budget_ratio * total_blocks))]
    stats = {"blocks": 0, "seqs": 0, "quarantined": 0, "retries": 0,
             "rework": sum(s["rework"] for s in shards),
             "commit_s": 0.0, "overlap_s": 0.0}
    t_run0 = time.perf_counter()

    def submit_block(st: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Submit one block's device compute and return its in-flight
        record, or None when the shard failed at submit (retries
        exhausted). Advances the shard's OPTIMISTIC counters only —
        `next`/`state` move at commit, never here, so the cursor can
        never get ahead of durable bytes."""
        shard = st["shard"]
        block_idx = st["pending_blocks"]
        start = st["pending_next"]
        end = min(start + block_size, st["hi"] - st["lo"])
        block_ids = [str(i) for i in ids[st["lo"] + start:st["lo"] + end]]
        block_seqs = list(seqs[st["lo"] + start:st["lo"] + end])

        quarantined: List[Tuple[str, str]] = []
        kept_ids: List[str] = []
        kept_seqs: List[str] = []
        for qid, seq in zip(block_ids, block_seqs):
            reason = poison_reason(seq)
            if reason is None:
                kept_ids.append(qid)
                kept_seqs.append(seq)
            else:
                quarantined.append((qid, reason))
                tele.metrics.counter("map_quarantined_total",
                                     reason=reason).inc()

        faults.block_latency()
        attempts = 0
        t0 = time.perf_counter()
        while True:
            try:
                if faults.take_failure(shard, block_idx):
                    raise TransientDispatchError(
                        f"injected dispatch failure (shard {shard} "
                        f"block {block_idx})")
                if kept_seqs:
                    fetch = _embed_block_submit(
                        params, cfg, kept_ids, kept_seqs,
                        rows_per_batch, max_segments, buckets)
                else:
                    def fetch() -> Dict[str, Any]:
                        return {
                            "ids": np.array([], dtype="S1"),
                            "lengths": np.zeros(0, np.int32),
                            "global": np.zeros((0, 1), np.float32),
                            "local_mean": np.zeros((0, 1), np.float32)}
                break
            except TransientDispatchError as e:
                stats["retries"] += 1
                tele.metrics.counter("map_retries_total").inc()
                attempts += 1
                budget[0] -= 1
                if attempts > retry_limit or budget[0] < 0:
                    st["failed"] = True
                    tele.emit("map_shard", shard=shard, state="failed",
                              reason=f"retries exhausted: {e}",
                              blocks=len(st["state"]["blocks"]))
                    logger.error("shard %d block %d: retries exhausted "
                                 "(%d attempts, budget %d): %s", shard,
                                 block_idx, attempts, budget[0], e)
                    return None
                delay = min(backoff_cap_s,
                            backoff_base_s * (2 ** (attempts - 1)))
                logger.warning("shard %d block %d: transient dispatch "
                               "failure (attempt %d/%d, retry in "
                               "%.3fs): %s", shard, block_idx, attempts,
                               retry_limit, delay, e)
                time.sleep(delay)

        st["pending_blocks"] = block_idx + 1
        st["pending_next"] = end
        return {"st": st, "shard": shard, "block": block_idx,
                "start": start, "end": end, "kept_ids": kept_ids,
                "quarantined": quarantined, "attempts": attempts,
                "t0": t0, "fetch": fetch}

    def commit_inflight(rec: Dict[str, Any], overlapped: bool) -> None:
        """Resolve one in-flight block: blocking host fetch, NaN gate,
        then the durable commit (object write → fsync → cursor
        advance) — the SAME ordered sequence as the serial path, so
        every crash window keeps its taxonomy. `overlapped` marks
        whether a later block's device compute was already enqueued
        when this ran (the pipelining evidence `map_overlap_ratio`
        reports)."""
        st = rec["st"]
        shard = rec["shard"]
        block_idx = rec["block"]
        if st["halted"]:
            # The predecessor block NaN-halted this shard at ITS commit
            # — committing this one would advance the cursor over a
            # hole. Discard the compute; the shard is already dead.
            logger.warning("shard %d block %d: discarding in-flight "
                           "block after shard halt", shard, block_idx)
            return
        tf0 = time.perf_counter()
        arrays = rec["fetch"]()
        start, end = rec["start"], rec["end"]
        kept_ids, quarantined = rec["kept_ids"], rec["quarantined"]
        attempts = rec["attempts"]
        t0 = rec["t0"]

        if faults.poison_output(shard, block_idx) \
                and arrays["global"].size:
            arrays = dict(arrays)
            arrays["global"] = arrays["global"].copy()
            arrays["global"][0, 0] = np.nan
        if not (np.isfinite(arrays["global"]).all()
                and np.isfinite(arrays["local_mean"]).all()):
            st["halted"] = True
            dump = tele.dump_flight("map_nan_halt") \
                if tele.enabled else None
            tele.emit("map_shard", shard=shard, state="halted",
                      reason="non_finite_embeddings",
                      block=block_idx, flight=dump)
            logger.error(
                "shard %d HALTED: block %d produced non-finite "
                "embeddings%s — the block was NOT committed", shard,
                block_idx,
                f" (flight dump: {dump})" if dump else "")
            return

        meta = {"shard": shard, "block": block_idx,
                "start": start, "end": end,
                "model_fingerprint": fingerprint}
        payload = serialize_block(meta, arrays)
        digest = block_digest(payload)
        entry = {"block": block_idx, "digest": digest, "start": start,
                 "end": end, "n": len(kept_ids),
                 "quarantined": [[q, r] for q, r in quarantined]}
        hook = faults.crash_hook(shard, block_idx)
        if hook is not None:
            # The pipelined split's new crash window (ISSUE 19): device
            # results are on the host but NOTHING is durable yet — a
            # kill here must cost exactly one block of re-work, same as
            # before_object.
            hook("block_fetched")
        st["state"] = commit_block(store, st["cursor"], st["state"],
                                   payload, entry, crash=hook)
        st["next"] = end
        dur = time.perf_counter() - t0
        commit_s = time.perf_counter() - tf0
        stats["commit_s"] += commit_s
        if overlapped:
            stats["overlap_s"] += commit_s
        if stats["commit_s"] > 0:
            tele.metrics.gauge("map_overlap_ratio").set(
                round(stats["overlap_s"] / stats["commit_s"], 4))
        rate = len(kept_ids) / dur if dur > 0 else 0.0
        stats["blocks"] += 1
        stats["seqs"] += len(kept_ids)
        stats["quarantined"] += len(quarantined)
        tele.metrics.counter("map_blocks_total", shard=shard).inc()
        tele.metrics.counter("map_seqs_total").inc(len(kept_ids))
        tele.metrics.gauge("map_seqs_per_s").set(round(rate, 3))
        size = max(1, st["hi"] - st["lo"])
        tele.metrics.gauge("map_shard_progress", shard=shard).set(
            round(end / size, 4))
        tele.emit("map_block", shard=shard, block=block_idx,
                  digest=digest, n=len(kept_ids), start=start, end=end,
                  quarantined=len(quarantined), retries=attempts,
                  seqs_per_s=round(rate, 3), dur_s=round(dur, 6))
        if st["next"] >= st["hi"] - st["lo"]:
            st["state"] = st["cursor"].write_state(
                dict(st["state"], done=True))
            tele.emit("map_shard", shard=shard, state="done",
                      blocks=len(st["state"]["blocks"]))

    # ---------------------------------------------------- the run loop
    # Round-robin over shards so progress (and therefore the worst-case
    # re-work after a kill) stays balanced, and so a chaos drill can
    # interleave faults across shards deterministically.
    #
    # Pipelined (ISSUE 19): ONE global in-flight slot. Each iteration
    # submits block N+1's device compute FIRST, then resolves + commits
    # block N — the host fetch and the durability I/O run while the
    # device chews on N+1. The slot is plain function-local state owned
    # by the single driver thread (no lock; nothing else can see it),
    # and commits still happen in exact submit order, so the per-shard
    # cursor invariant — never past an unfetched block — holds by
    # construction. A stop/preempt COMMITS the in-flight block before
    # returning (same contract as the serial path: finish the in-flight
    # block, flush the cursor, exit preempted).
    def runnable(st):
        return not (st["state"]["done"] or st["halted"] or st["failed"])

    def submittable(st):
        return runnable(st) and st["pending_next"] < st["hi"] - st["lo"]

    preempted = False
    inflight: List[Optional[Dict[str, Any]]] = [None]

    def drain_inflight() -> None:
        rec, inflight[0] = inflight[0], None
        if rec is not None:
            commit_inflight(rec, overlapped=False)

    def drive(stop_requested) -> None:
        nonlocal preempted
        processed = 0
        while any(submittable(s) for s in shards):
            advanced = False
            for st in shards:
                if not submittable(st):
                    continue
                if stop_requested():
                    preempted = True
                    drain_inflight()
                    return
                if max_blocks is not None and processed >= max_blocks:
                    preempted = True
                    drain_inflight()
                    return
                rec = submit_block(st)
                processed += 1
                advanced = True
                if rec is None:
                    continue  # shard failed at submit; nothing enqueued
                if not pipeline:
                    commit_inflight(rec, overlapped=False)
                    continue
                prev, inflight[0] = inflight[0], rec
                if prev is not None:
                    commit_inflight(prev, overlapped=True)
            if not advanced:
                break
        drain_inflight()

    if stop_flag is not None:
        drive(stop_flag)
    else:
        from proteinbert_tpu.train.resilience import GracefulShutdown

        with GracefulShutdown() as stop:
            drive(lambda: stop.requested)

    halted = [s["shard"] for s in shards if s["halted"]]
    failed = [s["shard"] for s in shards if s["failed"]]
    if halted:
        outcome = "halted"
    elif failed:
        outcome = "error"
    elif preempted or any(runnable(s) for s in shards):
        outcome = "preempted"
    else:
        outcome = "completed"
    wall = time.perf_counter() - t_run0
    result = {
        "outcome": outcome,
        "store": store.directory,
        "blocks": stats["blocks"],
        "seqs": stats["seqs"],
        "quarantined": stats["quarantined"],
        "retries": stats["retries"],
        "rework": stats["rework"],
        "halted_shards": halted,
        "failed_shards": failed,
        "wall_s": round(wall, 3),
        "seqs_per_s": round(stats["seqs"] / wall, 3) if wall > 0 else 0.0,
        # Pipelining evidence (ISSUE 19): the share of host
        # fetch+commit seconds spent while a later block's device
        # compute was already enqueued. On CPU the "device" shares the
        # host's cores, so this proves overlap happened, not that it
        # was free — wall_s is the honest speed number.
        "pipeline": bool(pipeline),
        "overlap_ratio": (round(stats["overlap_s"] / stats["commit_s"],
                                4)
                          if stats["commit_s"] > 0 else 0.0),
        "shards": [{
            "shard": s["shard"],
            "blocks": len(s["state"]["blocks"]),
            "consumed": s["next"],
            "size": s["hi"] - s["lo"],
            "done": s["state"]["done"],
        } for s in shards],
    }
    tele.emit("map_end", outcome=outcome,
              stats={k: v for k, v in result.items() if k != "shards"})
    return result
