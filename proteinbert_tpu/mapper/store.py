"""Integrity-verified, content-addressed embedding store (ISSUE 14).

The durable half of `pbt map`: block payloads are serialized into a
CANONICAL byte format (fixed magic + length-prefixed sorted-key JSON
header + raw C-order array bytes — no zip timestamps, so the same
inputs produce the same bytes on every run, which is what makes the
chaos drill's byte-identical-store gate possible), addressed by the
sha256 of those bytes under `objects/`, and owned by per-shard CURSORS
advanced only after the block they record is durably on disk.

Crash-safety contract (the whole point of this module):

- **Objects** are written tmp → flush → fsync → atomic rename. A crash
  mid-write leaves only a tmp file; `objects/<digest>` is either absent
  or complete.
- **Cursors** are small JSON documents carrying their own sha256
  (`sum`), written tmp → fsync → rename, with the PREVIOUS generation
  kept at `cursor.json.prev` (updated the same way) before every
  advance. A torn/corrupt main cursor therefore falls back exactly ONE
  generation — one block of re-work — and a torn prev on top of a torn
  main is the double-fault that restarts the shard (loudly).
- **Resume** re-verifies the TAIL block of each cursor (the only entry
  a crash window can leave half-true) and drops it when its object is
  missing or fails its digest — again at most one block of re-work.
- **Quarantine** sidecars are append-only JSONL with the events
  reader's torn-tail tolerance; the cursor's per-block quarantine lists
  stay authoritative (sidecar lines may duplicate across re-work and
  are deduplicated by id at read time).

`verify_store` recomputes every referenced digest and reports holes
(missing objects), corruption (digest mismatch / malformed payload),
and coverage gaps — the `pbt map --verify` pass.

Stdlib + numpy only (no jax): a store verifies on any machine that can
hold the artifacts, same contract as the obs package.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"PBTEMB1\n"
MANIFEST_VERSION = 1
CURSOR_VERSION = 1

CrashHook = Optional[Callable[[str], None]]


class StoreError(Exception):
    """Base class for typed store failures."""


class StoreConfigError(StoreError):
    """Manifest mismatch: the store on disk was written by a run with a
    different corpus/model/geometry than the resuming invocation."""


class BlockFormatError(StoreError):
    """A payload is not a well-formed canonical block."""


class BlockIntegrityError(StoreError):
    """A referenced object is missing, torn, or fails its digest.
    `reason` pinpoints which: "missing" | "digest_mismatch" |
    "malformed"."""

    def __init__(self, message: str, reason: str, digest: str = ""):
        super().__init__(message)
        self.reason = reason
        self.digest = digest


class CursorError(StoreError):
    """Both cursor generations are unreadable (double fault)."""


# ------------------------------------------------------- canonical blocks

def serialize_block(meta: Dict[str, Any],
                    arrays: Dict[str, np.ndarray]) -> bytes:
    """Canonical block bytes: MAGIC | u64 header length | header JSON
    (sorted keys, compact) | raw array bytes in header order. Arrays are
    laid down C-contiguous in sorted-name order; `meta` must be plain
    JSON-able scalars/lists."""
    entries = []
    chunks = []
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        entries.append({"name": name, "dtype": a.dtype.str,
                        "shape": list(a.shape)})
        chunks.append(a.tobytes())
    header = json.dumps({"meta": meta, "arrays": entries},
                        sort_keys=True, separators=(",", ":")).encode()
    return b"".join([MAGIC, struct.pack("<Q", len(header)), header,
                     *chunks])


def deserialize_block(data: bytes) -> Tuple[Dict[str, Any],
                                            Dict[str, np.ndarray]]:
    """Inverse of serialize_block; raises BlockFormatError on a bad
    magic, a torn tail, or trailing garbage."""
    if not data.startswith(MAGIC):
        raise BlockFormatError("bad magic: not a canonical block payload")
    off = len(MAGIC)
    if len(data) < off + 8:
        raise BlockFormatError("torn payload: truncated header length")
    (hlen,) = struct.unpack_from("<Q", data, off)
    off += 8
    if len(data) < off + hlen:
        raise BlockFormatError("torn payload: truncated header")
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise BlockFormatError(f"unparseable header: {e}") from None
    off += hlen
    arrays: Dict[str, np.ndarray] = {}
    for ent in header["arrays"]:
        dt = np.dtype(ent["dtype"])
        n = int(np.prod(ent["shape"], dtype=np.int64)) * dt.itemsize
        if len(data) < off + n:
            raise BlockFormatError(
                f"torn payload: array {ent['name']!r} truncated")
        arrays[ent["name"]] = np.frombuffer(
            data, dtype=dt, count=n // dt.itemsize if dt.itemsize else 0,
            offset=off).reshape(ent["shape"])
        off += n
    if off != len(data):
        raise BlockFormatError(f"{len(data) - off} trailing bytes after "
                               "the last declared array")
    return header["meta"], arrays


def block_digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


# ------------------------------------------------------- atomic file I/O

def _atomic_write(path: str, data: bytes, crash: CrashHook = None,
                  tmp_point: str = "", done_point: str = "") -> None:
    """tmp → flush → fsync → rename; `crash(point)` fires between the
    named filesystem boundaries (the drill/test kill seam)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if crash is not None and tmp_point:
        crash(tmp_point)
    os.replace(tmp, path)
    if crash is not None and done_point:
        crash(done_point)


def shard_ranges(n: int, num_shards: int) -> List[Tuple[int, int]]:
    """Deterministic contiguous split of corpus indices [0, n) into
    `num_shards` [start, end) ranges (first shards take the remainder).
    Shared by the engine and verify so they can never disagree."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    base, rem = divmod(n, num_shards)
    ranges = []
    start = 0
    for s in range(num_shards):
        size = base + (1 if s < rem else 0)
        ranges.append((start, start + size))
        start += size
    return ranges


def corpus_digest(ids, seqs) -> str:
    """Content identity of a corpus: sha256 over (id, sequence) pairs in
    order. Non-string poison entries hash by repr so a poisoned corpus
    still has a stable identity."""
    h = hashlib.sha256()
    for i, s in zip(ids, seqs):
        h.update(str(i).encode())
        h.update(b"\x00")
        h.update(s.encode() if isinstance(s, str) else repr(s).encode())
        h.update(b"\x01")
    return h.hexdigest()


# --------------------------------------------------------------- cursors

class ShardCursor:
    """One shard's crash-safe progress record (see module docstring for
    the write protocol). The cursor STATE is a plain dict the engine
    holds; this class owns the disk representation."""

    def __init__(self, store_dir: str, shard: int):
        self.shard = int(shard)
        self.directory = os.path.join(os.path.abspath(store_dir),
                                      "shards", str(self.shard))
        self.path = os.path.join(self.directory, "cursor.json")
        self.prev_path = self.path + ".prev"
        self.quarantine_path = os.path.join(self.directory,
                                            "quarantine.jsonl")

    def fresh_state(self) -> Dict[str, Any]:
        return {"v": CURSOR_VERSION, "shard": self.shard, "gen": 0,
                "blocks": [], "done": False}

    @staticmethod
    def _checksum(state: Dict[str, Any]) -> str:
        body = {k: v for k, v in state.items() if k != "sum"}
        canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canon.encode()).hexdigest()

    def _parse(self, raw: bytes) -> Dict[str, Any]:
        state = json.loads(raw)
        if not isinstance(state, dict):
            raise ValueError("cursor is not an object")
        if state.get("v") != CURSOR_VERSION:
            raise ValueError(f"cursor version {state.get('v')!r} != "
                             f"{CURSOR_VERSION}")
        if state.get("shard") != self.shard:
            raise ValueError(f"cursor shard {state.get('shard')!r} != "
                             f"{self.shard}")
        if state.get("sum") != self._checksum(state):
            raise ValueError("cursor checksum mismatch (torn or "
                             "corrupted write)")
        state.pop("sum", None)
        return state

    def load(self) -> Tuple[Dict[str, Any], str]:
        """(state, source) where source ∈ {"main", "prev", "fresh"}.
        A torn main cursor falls back one generation to `prev` (≤ one
        block of re-work); both torn raises CursorError — silently
        restarting a multi-day shard from zero is never the right
        default."""
        errors = []
        for path, source in ((self.path, "main"),
                             (self.prev_path, "prev")):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                if source == "main" and not os.path.exists(self.prev_path):
                    return self.fresh_state(), "fresh"
                errors.append(f"{path}: missing")
                continue
            try:
                state = self._parse(raw)
            except ValueError as e:
                errors.append(f"{path}: {e}")
                logger.warning("shard %d cursor %s unreadable (%s)",
                               self.shard, source, e)
                continue
            if source == "prev":
                logger.warning(
                    "shard %d: main cursor torn — resuming from the "
                    "previous generation (gen %d, %d block(s); at most "
                    "one block of re-work)", self.shard, state["gen"],
                    len(state["blocks"]))
            return state, source
        raise CursorError(
            f"shard {self.shard}: both cursor generations unreadable "
            f"({'; '.join(errors)}) — refusing to silently restart the "
            "shard; delete its shards/ directory to start it over")

    def write_state(self, state: Dict[str, Any],
                    crash: CrashHook = None) -> Dict[str, Any]:
        """Persist `state` as the next generation: serialize + checksum,
        copy the current main to `.prev`, then atomically replace main.
        Returns the state as written (gen bumped). Crash points:
        cursor_serialized / cursor_prev_updated / cursor_tmp_written /
        cursor_renamed."""
        os.makedirs(self.directory, exist_ok=True)
        state = dict(state, gen=int(state.get("gen", 0)) + 1)
        state["sum"] = self._checksum(state)
        data = json.dumps(state, sort_keys=True).encode()
        if crash is not None:
            crash("cursor_serialized")
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                _atomic_write(self.prev_path, f.read())
        if crash is not None:
            crash("cursor_prev_updated")
        _atomic_write(self.path, data, crash=crash,
                      tmp_point="cursor_tmp_written",
                      done_point="cursor_renamed")
        state.pop("sum", None)
        return state

    # ------------------------------------------------ quarantine sidecar

    def append_quarantine(self, shard_block: int,
                          records: List[Tuple[str, str]]) -> None:
        """Append (id, reason) rows; line-buffered like the event log (a
        crash tears at most the last line)."""
        if not records:
            return
        os.makedirs(self.directory, exist_ok=True)
        with open(self.quarantine_path, "a", buffering=1) as f:
            for qid, reason in records:
                f.write(json.dumps({"shard": self.shard,
                                    "block": int(shard_block),
                                    "id": str(qid),
                                    "reason": reason}) + "\n")

    def read_quarantine(self) -> List[Dict[str, Any]]:
        """Sidecar rows, deduplicated by id (re-worked blocks append
        their quarantines again), torn-tail tolerant like read_events."""
        if not os.path.exists(self.quarantine_path):
            return []
        with open(self.quarantine_path) as f:
            lines = [ln for ln in f if ln.strip()]
        out: Dict[str, Dict[str, Any]] = {}
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    break  # torn tail from a crash mid-append
                logger.warning("%s: skipping unparseable quarantine "
                               "line %d", self.quarantine_path, i + 1)
                continue
            out[str(rec.get("id"))] = rec
        return list(out.values())


# ----------------------------------------------------------------- store

class EmbeddingStore:
    """Directory handle: manifest + content-addressed objects +
    per-shard cursors."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        self.manifest_path = os.path.join(self.directory, "manifest.json")
        self.objects_dir = os.path.join(self.directory, "objects")

    # ------------------------------------------------------- manifest

    def ensure_manifest(self, manifest: Dict[str, Any]) -> Dict[str, Any]:
        """Create the manifest atomically, or validate that an existing
        one matches — resuming against a different corpus, model, or
        geometry is a typed StoreConfigError, not silent garbage."""
        manifest = dict(manifest, v=MANIFEST_VERSION)
        existing = self.load_manifest()
        if existing is None:
            os.makedirs(self.directory, exist_ok=True)
            _atomic_write(self.manifest_path,
                          json.dumps(manifest, sort_keys=True,
                                     indent=1).encode())
            return manifest
        diffs = [k for k in sorted(set(manifest) | set(existing))
                 if manifest.get(k) != existing.get(k)]
        if diffs:
            raise StoreConfigError(
                f"store {self.directory} was written with a different "
                f"configuration — mismatched manifest field(s) "
                f"{diffs}: "
                + "; ".join(f"{k}: store={existing.get(k)!r} "
                            f"run={manifest.get(k)!r}" for k in diffs))
        return existing

    def load_manifest(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.manifest_path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except ValueError as e:
            raise StoreConfigError(
                f"{self.manifest_path} is unreadable ({e})") from None

    # -------------------------------------------------------- objects

    def object_path(self, digest: str) -> str:
        return os.path.join(self.objects_dir, digest[:2], digest)

    def write_object(self, payload: bytes, digest: str) -> bool:
        """Idempotent content-addressed write; returns True when bytes
        hit disk. An existing object with MATCHING bytes is skipped; an
        existing object with WRONG bytes (a torn/corrupted survivor a
        resume is re-working) is overwritten."""
        path = self.object_path(digest)
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    if block_digest(f.read()) == digest:
                        return False
            except OSError:
                pass
            logger.warning("object %s exists but fails its digest — "
                           "rewriting", digest[:16])
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _atomic_write(path, payload)
        return True

    def read_object(self, digest: str) -> bytes:
        """Digest-verified read; BlockIntegrityError("missing" |
        "digest_mismatch") otherwise."""
        path = self.object_path(digest)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise BlockIntegrityError(
                f"object {digest[:16]}… is missing (hole)",
                reason="missing", digest=digest) from None
        if block_digest(data) != digest:
            raise BlockIntegrityError(
                f"object {digest[:16]}… fails its sha256 (flipped or "
                "torn bytes)", reason="digest_mismatch", digest=digest)
        return data

    def read_block(self, digest: str) -> Tuple[Dict[str, Any],
                                               Dict[str, np.ndarray]]:
        data = self.read_object(digest)
        try:
            return deserialize_block(data)
        except BlockFormatError as e:
            raise BlockIntegrityError(
                f"object {digest[:16]}…: {e}", reason="malformed",
                digest=digest) from None


# ------------------------------------------------- the commit protocol

def commit_block(store: EmbeddingStore, cursor: ShardCursor,
                 state: Dict[str, Any], payload: bytes,
                 entry: Dict[str, Any],
                 crash: CrashHook = None) -> Dict[str, Any]:
    """THE durability protocol of `pbt map`, in one place so the engine
    and the atomicity tests exercise identical code: quarantine sidecar
    append → object write (tmp+fsync+rename) → cursor advance
    (prev-generation copy, then atomic replace). The cursor is the
    commit point: a kill ANYWHERE in here loses at most this block.
    Returns the advanced cursor state."""
    digest = entry["digest"]
    cursor.append_quarantine(entry["block"],
                             entry.get("quarantined") or [])
    if crash is not None:
        crash("before_object")
    store.write_object(payload, digest)
    if crash is not None:
        crash("after_object")
    new_state = dict(state)
    new_state["blocks"] = list(state["blocks"]) + [entry]
    return cursor.write_state(new_state, crash=crash)


def resume_shard(store: EmbeddingStore,
                 shard: int) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load a shard's cursor for resumption and re-verify its TAIL
    block (the only entry a crash window can leave half-true: a torn
    object can only be the in-flight write, and a cursor fallback only
    drops the newest entry). A bad tail is dropped — that block is
    re-worked. Returns (state, info) with info = {"source",
    "tail_dropped": entry|None}."""
    cursor = ShardCursor(store.directory, shard)
    state, source = cursor.load()
    info: Dict[str, Any] = {"source": source, "tail_dropped": None}
    if state["blocks"]:
        tail = state["blocks"][-1]
        try:
            store.read_object(tail["digest"])
        except BlockIntegrityError as e:
            logger.warning(
                "shard %d: tail block %d (%s…) failed verification on "
                "resume (%s) — re-working it", shard, tail["block"],
                tail["digest"][:16], e.reason)
            state = dict(state, blocks=state["blocks"][:-1], done=False)
            state = cursor.write_state(state)
            info["tail_dropped"] = tail
    return state, info


def next_offset(state: Dict[str, Any]) -> int:
    """Shard-local index the next block starts at (blocks are
    contiguous by construction)."""
    return int(state["blocks"][-1]["end"]) if state["blocks"] else 0


# ----------------------------------------------------------- verification

def verify_store(store_dir: str) -> Dict[str, Any]:
    """Recompute every referenced digest and audit coverage — the
    `pbt map --verify` pass. Never raises for content problems (they
    land in the report, ok=False); a missing/corrupt manifest raises
    StoreConfigError because nothing else is interpretable without it."""
    store = EmbeddingStore(store_dir)
    manifest = store.load_manifest()
    if manifest is None:
        raise StoreConfigError(f"{store_dir} has no manifest.json — "
                               "not an embedding store")
    n = int(manifest["corpus_n"])
    num_shards = int(manifest["num_shards"])
    ranges = shard_ranges(n, num_shards)
    holes: List[Dict[str, Any]] = []
    corrupt: List[Dict[str, Any]] = []
    coverage_errors: List[str] = []
    shards_out: List[Dict[str, Any]] = []
    blocks_checked = 0
    seqs = 0
    quarantined_ids: set = set()
    all_done = True
    for shard, (lo, hi) in enumerate(ranges):
        cursor = ShardCursor(store_dir, shard)
        try:
            state, source = cursor.load()
        except CursorError as e:
            coverage_errors.append(str(e))
            all_done = False
            shards_out.append({"shard": shard, "error": str(e)})
            continue
        expected_start = 0
        for entry in state["blocks"]:
            blocks_checked += 1
            if entry["start"] != expected_start:
                coverage_errors.append(
                    f"shard {shard} block {entry['block']}: starts at "
                    f"{entry['start']}, expected {expected_start} "
                    "(gap or overlap)")
            expected_start = entry["end"]
            for qid, _reason in entry.get("quarantined") or []:
                quarantined_ids.add(str(qid))
            seqs += int(entry["n"])
            try:
                meta, arrays = store.read_block(entry["digest"])
            except BlockIntegrityError as e:
                rec = {"shard": shard, "block": entry["block"],
                       "digest": entry["digest"], "reason": e.reason}
                (holes if e.reason == "missing" else corrupt).append(rec)
                continue
            if int(arrays["ids"].shape[0]) != int(entry["n"]):
                corrupt.append({"shard": shard, "block": entry["block"],
                                "digest": entry["digest"],
                                "reason": "row_count_mismatch"})
        consumed = next_offset(state)
        if state["done"] and consumed != hi - lo:
            coverage_errors.append(
                f"shard {shard} marked done at {consumed}/{hi - lo} "
                "sequences")
        if not state["done"]:
            all_done = False
        shards_out.append({
            "shard": shard, "size": hi - lo, "consumed": consumed,
            "blocks": len(state["blocks"]), "done": state["done"],
            "cursor_source": source,
        })
    embedded = seqs  # rows in blocks exclude quarantined by contract
    report = {
        "store": store.directory,
        "manifest": manifest,
        "shards": shards_out,
        "blocks_checked": blocks_checked,
        "embedded": embedded,
        "quarantined": len(quarantined_ids),
        "holes": holes,
        "corrupt": corrupt,
        "coverage_errors": coverage_errors,
        "complete": all_done,
    }
    report["ok"] = not (holes or corrupt or coverage_errors)
    return report


def store_digests(store_dir: str) -> Dict[Tuple[int, int], str]:
    """{(shard, block): digest} over every cursor — the drill's
    byte-identity comparison key."""
    store = EmbeddingStore(store_dir)
    manifest = store.load_manifest()
    if manifest is None:
        raise StoreConfigError(f"{store_dir} has no manifest.json")
    out: Dict[Tuple[int, int], str] = {}
    for shard in range(int(manifest["num_shards"])):
        state, _ = ShardCursor(store_dir, shard).load()
        for entry in state["blocks"]:
            out[(shard, int(entry["block"]))] = entry["digest"]
    return out


def iter_embeddings(store_dir: str):
    """Yield (id, lengths-aware record dict) per embedded sequence, in
    corpus order per shard — the minimal read API for downstream
    consumers (the ROADMAP-4 neighbor index builds on it)."""
    store = EmbeddingStore(store_dir)
    manifest = store.load_manifest()
    if manifest is None:
        raise StoreConfigError(f"{store_dir} has no manifest.json")
    for shard in range(int(manifest["num_shards"])):
        state, _ = ShardCursor(store_dir, shard).load()
        for entry in state["blocks"]:
            _meta, arrays = store.read_block(entry["digest"])
            for i in range(arrays["ids"].shape[0]):
                yield (arrays["ids"][i].decode(), {
                    "length": int(arrays["lengths"][i]),
                    "global": arrays["global"][i],
                    "local_mean": arrays["local_mean"][i],
                })
