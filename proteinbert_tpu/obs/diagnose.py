"""Run diagnosis from a telemetry events stream (+ optional flight dump).

The analysis behind `pbt diagnose`: given the JSONL a run emitted (and,
for a dead run, its flight-recorder dump), answer the operator
questions one artifact at a time used to need four — how fast was it
going, where did it stall, how much boundary work ran hidden, and what
happened right before it died.

Pure functions over plain dicts (no jax), so this also serves as the
library API for notebooks and the test suite.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional


# One rank convention for the whole obs package: a p99 here must equal
# the registry window's p99 for the same data.
from proteinbert_tpu.obs.metrics import nearest_rank as _percentile


def summarize(records: List[Dict[str, Any]],
              flight: Optional[Dict[str, Any]] = None,
              slow_top: int = 5, last: int = 10) -> Dict[str, Any]:
    """One JSON-able summary dict; every section is optional-input-safe
    (a partial stream from a dead run still summarizes).

    A requeued run appends a fresh run_start to the SAME file (that is
    the exit-75 flow); rates/wall/manifest are computed over the LAST
    incarnation only — mixing incarnations would divide step counts by
    wall time that includes the queue/restart gap and report the dead
    pid's manifest. Earlier incarnations stay visible via `counts`
    (whole file) and `incarnations`."""
    starts = [i for i, r in enumerate(records) if r["event"] == "run_start"]
    incarnations = len(starts)
    whole_file_counts = dict(
        collections.Counter(r["event"] for r in records))
    if len(starts) > 1:
        records = records[starts[-1]:]
    steps = [r for r in records if r["event"] == "step"]
    evals = [r for r in records if r["event"] == "eval"]
    ckpt = [r for r in records if r["event"] == "ckpt_stage"]
    run_start = next((r for r in records if r["event"] == "run_start"), None)
    run_end = next((r for r in reversed(records)
                    if r["event"] == "run_end"), None)

    out: Dict[str, Any] = {
        "counts": whole_file_counts,
        "incarnations": incarnations,
        "outcome": (run_end["outcome"] if run_end
                    else "unknown (no run_end record — died hard?)"),
    }
    if run_start is not None:
        out["manifest"] = {
            "jax_version": run_start.get("jax_version"),
            "pid": run_start.get("pid"),
            "mesh": run_start.get("mesh"),
            "n_chips": run_start.get("n_chips"),
            "resumed": run_start.get("resumed"),
        }

    # ------------------------------------------------------ step rate
    # Cumulative rate straight from StepTimer (run_end.perf, else the
    # last step record), PLUS an independent wall-clock estimate from
    # the stream's own stamps — a disagreement between the two is
    # itself a finding (timer discounting hiding real stall time).
    perf = dict((run_end or {}).get("perf") or {})
    if not perf and steps:
        perf = {k: v for k, v in steps[-1]["metrics"].items()
                if isinstance(v, (int, float))}
    rate: Dict[str, Any] = {"steps_per_sec": perf.get("steps_per_sec")}
    if len(steps) >= 2:
        d_steps = steps[-1]["step"] - steps[0]["step"]
        d_t = steps[-1]["t"] - steps[0]["t"]
        if d_steps > 0 and d_t > 0:
            rate["stream_steps_per_sec"] = d_steps / d_t
    windows = [(s["step"], s["metrics"]["window_steps_per_sec"])
               for s in steps
               if isinstance(s["metrics"].get("window_steps_per_sec"),
                             (int, float))]
    if windows:
        rate["window_trend"] = [(st, round(w, 4)) for st, w in windows]
        half = len(windows) // 2
        if half:
            first = sum(w for _, w in windows[:half]) / half
            second = sum(w for _, w in windows[half:]) / (len(windows) - half)
            ratio = second / first if first > 0 else 1.0
            rate["trend"] = ("degrading" if ratio < 0.9
                            else "improving" if ratio > 1.1 else "stable")
    out["step_rate"] = rate

    # ------------------------------------------------- stall top-list
    slow = sorted(
        (s for s in steps
         if isinstance(s["metrics"].get("window_step_ms"), (int, float))),
        key=lambda s: -s["metrics"]["window_step_ms"])[:slow_top]
    out["stalls"] = [{
        "step": s["step"],
        "window_step_ms": round(s["metrics"]["window_step_ms"], 2),
        "ckpt_in_flight": bool(s["metrics"].get("ckpt_in_flight")),
        "t": s["t"],
    } for s in slow]

    # -------------------------------------------- boundary overlap
    landed = [c for c in ckpt if c.get("phase") == "landed"]
    landed_overlap = sum(c.get("overlap_s") or 0.0 for c in landed)
    overlap_s = perf.get("overlap_s", landed_overlap)
    wall = None
    if run_start is not None and run_end is not None:
        wall = run_end["t"] - run_start["t"]
    elif len(records) >= 2:
        wall = records[-1]["t"] - records[0]["t"]
    out["boundary"] = {
        "ckpt_stages_landed": len(landed),
        "overlap_s": round(overlap_s, 4),
        "landed_overlap_s": round(landed_overlap, 4),
        "evals": len(evals),
        "wall_s": round(wall, 3) if wall is not None else None,
        "overlap_ratio": (round(overlap_s / wall, 6)
                          if wall and wall > 0 else None),
    }

    # ------------------------------------------- death forensics
    tail_src: List[Dict[str, Any]] = records
    if flight is not None:
        out["flight"] = {"reason": flight.get("reason"),
                         "pid": flight.get("pid"),
                         "dumped_at": flight.get("dumped_at"),
                         "events": len(flight.get("events") or [])}
        tail_src = flight.get("events") or records
    out["last_events"] = [{
        "event": r["event"], "step": r.get("step"), "t": r["t"],
        **({"phase": r["phase"]} if r["event"] == "ckpt_stage" else {}),
        **({"reason": r["reason"]} if r["event"] == "requeue" else {}),
        **({"outcome": r["outcome"]} if r["event"] == "run_end" else {}),
    } for r in tail_src[-last:]]
    return out


def summarize_serve(records: List[Dict[str, Any]],
                    slow_top: int = 5) -> Dict[str, Any]:
    """The `pbt diagnose --serve` section: request outcomes, latency
    percentiles, per-stage time attribution, and SLO breaches from the
    serve_* records of a stream (ISSUE 6). Optional-input-safe like
    summarize(): a stream with only a manifest still summarizes."""
    start = next((r for r in records if r["event"] == "serve_start"), None)
    end = next((r for r in reversed(records)
                if r["event"] == "serve_end"), None)
    reqs = [r for r in records if r["event"] == "serve_request"]
    rejects = [r for r in records if r["event"] == "serve_reject"]
    batches = [r for r in records if r["event"] == "serve_batch"]
    breaches = [r for r in records if r["event"] == "slo_breach"]

    out: Dict[str, Any] = {
        "manifest": (start.get("config") if start else None),
        "outcome": (end["outcome"] if end
                    else "unknown (no serve_end record)"),
        "requests_traced": len(reqs),
        "outcomes": dict(collections.Counter(r["outcome"] for r in reqs)),
    }

    # ---- end-to-end latency + per-stage attribution (traced reqs) ----
    e2e = sorted(r["e2e_s"] for r in reqs
                 if isinstance(r.get("e2e_s"), (int, float)))
    out["e2e"] = {
        "n": len(e2e),
        "p50_s": _percentile(e2e, 0.50),
        "p99_s": _percentile(e2e, 0.99),
        "max_s": e2e[-1] if e2e else None,
    }
    stage_sums: Dict[str, float] = collections.defaultdict(float)
    for r in reqs:
        for stage, dur in (r.get("stages") or {}).items():
            if isinstance(dur, (int, float)):
                stage_sums[stage] += dur
        # Padding waste is attribution, not a wall-clock stage: it
        # overlaps `execute`, so it is reported beside the stages.
        pf, ex = r.get("pad_fraction"), (r.get("stages") or {}).get(
            "execute")
        if isinstance(pf, (int, float)) and isinstance(ex, (int, float)):
            stage_sums["pad_wasted(of execute)"] += pf * ex
    total = sum(v for k, v in stage_sums.items() if "(" not in k)
    out["stage_attribution"] = {
        k: {"total_s": round(v, 6),
            "share": round(v / total, 4) if total else None}
        for k, v in sorted(stage_sums.items(), key=lambda kv: -kv[1])
    }

    # ---- slowest traced requests, with the stage to blame ----
    slow = sorted((r for r in reqs
                   if isinstance(r.get("e2e_s"), (int, float))),
                  key=lambda r: -r["e2e_s"])[:slow_top]
    out["slowest"] = [{
        "request_id": r.get("request_id"),
        "kind": r["kind"],
        "outcome": r["outcome"],
        "e2e_s": round(r["e2e_s"], 6),
        "dominant_stage": (max(r["stages"], key=r["stages"].get)
                           if r.get("stages") else None),
        "bucket_len": r.get("bucket_len"),
        "batch_class": r.get("batch_class"),
    } for r in slow]

    # ---- per-head attribution (multi-tenant serving, ISSUE 8) ----
    # One tenant's slow or erroring head must be attributable: group
    # the traced requests by head_id (predict_task requests carry one;
    # errors/rejections ALWAYS emit regardless of sampling, so error
    # attribution is complete even at low sample rates).
    by_head: Dict[str, List[Dict[str, Any]]] = {}
    for r in reqs:
        hid = r.get("head_id")
        if isinstance(hid, str):
            by_head.setdefault(hid, []).append(r)
    per_head: Dict[str, Any] = {}
    for hid, rs in sorted(by_head.items()):
        lat = sorted(r["e2e_s"] for r in rs
                     if isinstance(r.get("e2e_s"), (int, float)))
        outcomes = dict(collections.Counter(r["outcome"] for r in rs))
        per_head[hid] = {
            "n": len(rs),
            "outcomes": outcomes,
            "errors": sum(v for k, v in outcomes.items()
                          if k not in ("ok", "cache_hit")),
            "p50_s": _percentile(lat, 0.50),
            "p99_s": _percentile(lat, 0.99),
        }
    out["per_head"] = per_head
    head_rejects = collections.Counter(
        r["head_id"] for r in rejects
        if r.get("reason") == "unknown_head"
        and isinstance(r.get("head_id"), str))
    out["unknown_head_rejects"] = dict(head_rejects)

    # ---- rejections (with queue depth where the emitter knew it) ----
    depths = [r["queue_depth"] for r in rejects
              if isinstance(r.get("queue_depth"), int)]
    out["rejects"] = {
        "total": len(rejects),
        "by_reason": dict(collections.Counter(r["reason"]
                                              for r in rejects)),
        "queue_depth_max": max(depths) if depths else None,
        "queue_depth_mean": (round(sum(depths) / len(depths), 2)
                             if depths else None),
    }

    # ---- batches ----
    rows = [b["rows"] for b in batches]
    occ = [b["rows"] / b["batch_class"] for b in batches
           if isinstance(b.get("batch_class"), int) and b["batch_class"]]
    pads = [b["pad_fraction"] for b in batches
            if isinstance(b.get("pad_fraction"), (int, float))]
    segs = [b["segments"] for b in batches
            if isinstance(b.get("segments"), int)]
    spr = [b["segments_per_row"] for b in batches
           if isinstance(b.get("segments_per_row"), (int, float))]
    out["batches"] = {
        "n": len(batches),
        "rows": sum(rows),
        "mean_rows": round(sum(rows) / len(rows), 2) if rows else None,
        "mean_occupancy": (round(sum(occ) / len(occ), 4)
                           if occ else None),
        "mean_pad_fraction": (round(sum(pads) / len(pads), 4)
                              if pads else None),
        # Ragged packed batches (ISSUE 9): requests per batch and per
        # row — absent on a purely bucketed stream.
        "modes": dict(collections.Counter(
            b["mode"] for b in batches if isinstance(b.get("mode"), str))),
        "segments": sum(segs) if segs else None,
        "mean_segments_per_row": (round(sum(spr) / len(spr), 4)
                                  if spr else None),
    }

    # ---- executable zoo + fused-kernel path coverage (ISSUE 9/10) ----
    # From the terminal stats snapshot: warm executable count (the
    # bucketed |buckets|x|classes|xkinds ladder vs ragged O(kinds)),
    # cumulative warmup seconds, and the two-sided fused-kernel path
    # counts — how many executables ran the Pallas fast path vs the XLA
    # reference (coverage, not just misses). `fused_fallback` only
    # appears in HISTORICAL stats snapshots (the deprecated one-sided
    # counter was removed in ISSUE 12); it is read here so old event
    # streams still diagnose, never emitted anymore.
    end_stats = (end.get("stats") if end is not None
                 and isinstance(end.get("stats"), dict) else None)
    if end_stats is not None:
        out["executables"] = {
            "serve_mode": end_stats.get("serve_mode"),
            "count": end_stats.get("executables"),
            "warmup_seconds": end_stats.get("warmup_seconds"),
            "fused_path": end_stats.get("fused_path"),
            "attention_path": end_stats.get("attention_path"),
            "onepass_path": end_stats.get("onepass_path"),
            "fused_fallback": end_stats.get("fused_fallback"),
        }

    # ---- /v1/neighbors attribution (ISSUE 17) ----
    # Neighbors requests carry a `lookup` stage between execute and
    # finalize; the stage set still tiles e2e by construction, so the
    # embed leg (submit..execute) and the lookup leg split each traced
    # request's latency exactly — no extra instrumentation needed.
    nreqs = [r for r in reqs if r.get("kind") == "neighbors"]
    nqueries = [r for r in records if r["event"] == "neighbor_query"]
    if nreqs or nqueries:
        embed_names = ("submit", "queue", "batch_form", "dispatch",
                       "execute")

        def _leg(r: Dict[str, Any]) -> float:
            return sum(v for k, v in (r.get("stages") or {}).items()
                       if k in embed_names
                       and isinstance(v, (int, float)))

        served = [r for r in nreqs
                  if isinstance((r.get("stages") or {}).get("lookup"),
                                (int, float))]
        embed_leg = sorted(_leg(r) for r in served)
        lookup_leg = sorted(r["stages"]["lookup"] for r in served)
        outcomes = collections.Counter(r["outcome"] for r in nreqs)
        n_out = sum(outcomes.values())
        lookups = [q["lookup_s"] for q in nqueries
                   if isinstance(q.get("lookup_s"), (int, float))]
        cands = [q["candidates"] for q in nqueries
                 if isinstance(q.get("candidates"), int)]
        nb: Dict[str, Any] = {
            "requests_traced": len(nreqs),
            "outcomes": dict(outcomes),
            "cache_hit_rate": (round(outcomes.get("cache_hit", 0)
                                     / n_out, 4) if n_out else None),
            "embed_leg": {"n": len(embed_leg),
                          "p50_s": _percentile(embed_leg, 0.50),
                          "p99_s": _percentile(embed_leg, 0.99)},
            "lookup_leg": {"n": len(lookup_leg),
                           "p50_s": _percentile(lookup_leg, 0.50),
                           "p99_s": _percentile(lookup_leg, 0.99)},
            "queries": len(nqueries),
            "mean_lookup_s": (round(sum(lookups) / len(lookups), 6)
                              if lookups else None),
            "mean_candidates": (round(sum(cands) / len(cands), 1)
                                if cands else None),
        }
        if end_stats is not None \
                and isinstance(end_stats.get("neighbors"), dict):
            nb["final"] = end_stats["neighbors"]
        out["neighbors"] = nb
    else:
        out["neighbors"] = None

    # ---- SLO breaches ----
    out["slo_breaches"] = [{
        "objective": b["objective"], "burn_rate": b["burn_rate"],
        "bad": b.get("bad"), "total": b.get("total"), "t": b["t"],
    } for b in breaches]
    if end is not None and isinstance(end.get("stats"), dict):
        out["final_slo"] = end["stats"].get("slo")
    return out


def _fleet_chains(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Group a (merged) stream's fleet_request / fleet_attempt /
    serve_request records into per-trace causal chains (ISSUE 18).

    The join key is `trace_id` — the router-minted id every record in
    one request's life carries. Replica-side serve_request records are
    matched onto attempts by `replica_id` in attempt order (the router
    never has two concurrent attempts of one trace on one replica).
    `complete` encodes the drill's reconstruction contract: sealed
    exactly once, attempts on record == retries spent + 1, and an
    ok/retried_ok chain ends in an attempt that succeeded."""
    chains: Dict[str, Dict[str, Any]] = {}

    def chain(tid: str) -> Dict[str, Any]:
        c = chains.get(tid)
        if c is None:
            c = chains[tid] = {
                "trace_id": tid, "seals": 0, "outcome": None,
                "status": None, "path": None, "retries": None,
                "replica": None, "sealed_t": None, "attempts": [],
                "_serve": []}
        return c

    for r in records:
        ev = r.get("event")
        if ev == "fleet_request":
            tid = r.get("trace_id") or r.get("request_id")
            if not isinstance(tid, str):
                continue
            c = chain(tid)
            c["seals"] += 1
            c["outcome"] = r.get("outcome")
            c["status"] = r.get("status")
            c["path"] = r.get("path")
            c["retries"] = r.get("retries")
            c["replica"] = r.get("replica")
            c["sealed_t"] = r.get("t")
        elif ev == "fleet_attempt":
            tid = r.get("trace_id")
            if not isinstance(tid, str):
                continue
            chain(tid)["attempts"].append({
                "attempt": r.get("attempt"), "replica": r.get("replica"),
                "outcome": r.get("outcome"), "status": r.get("status"),
                "backoff_s": r.get("backoff_s"), "t": r.get("t"),
                "serve": None})
        elif ev == "serve_request":
            tid = r.get("trace_id")
            if isinstance(tid, str):
                chain(tid)["_serve"].append(r)

    for c in chains.values():
        c["attempts"].sort(
            key=lambda a: (a["attempt"] is None, a["attempt"]))
        unmatched = list(c.pop("_serve"))
        for a in c["attempts"]:
            for i, s in enumerate(unmatched):
                if s.get("replica_id") == a["replica"]:
                    a["serve"] = {
                        "request_id": s.get("request_id"),
                        "outcome": s.get("outcome"),
                        "e2e_s": s.get("e2e_s"),
                        "stages": s.get("stages"),
                        "t": s.get("t"),
                    }
                    unmatched.pop(i)
                    break
        c["unmatched_serve"] = len(unmatched)
        n_att = len(c["attempts"])
        ok_chain = (c["seals"] == 1
                    and (not n_att or c["retries"] is None
                         or n_att == c["retries"] + 1))
        if ok_chain and n_att and c["outcome"] in ("ok", "retried_ok"):
            ok_chain = c["attempts"][-1]["outcome"] == "ok"
        c["complete"] = ok_chain
    return chains


def summarize_fleet(records: List[Dict[str, Any]],
                    trace_id: Optional[str] = None,
                    slow_top: int = 5) -> Dict[str, Any]:
    """The `pbt diagnose --fleet` section: per-trace causal chains
    (admission → attempts → sealed) over a MERGED fleet stream, the
    exactly-once-sealing and attempt-accounting audits, and replica
    lifecycle context (ISSUE 18). `trace_id` selects one chain for
    full rendering. Optional-input-safe like the other summarizers —
    an un-merged single-process stream still summarizes (it simply has
    no attempts to join)."""
    start = next((r for r in records if r["event"] == "fleet_start"),
                 None)
    end = next((r for r in reversed(records)
                if r["event"] == "fleet_end"), None)
    transitions = [r for r in records if r["event"] == "fleet_replica"]
    chains = _fleet_chains(records)

    seal_violations = {tid: c["seals"] for tid, c in chains.items()
                       if c["seals"] != 1}
    mismatched = [tid for tid, c in chains.items()
                  if c["attempts"] and c["retries"] is not None
                  and len(c["attempts"]) != c["retries"] + 1]
    out: Dict[str, Any] = {
        "manifest": (start.get("config") if start else None),
        "outcome": (end["outcome"] if end
                    else "unknown (no fleet_end record)"),
        "traces": len(chains),
        "outcomes": dict(collections.Counter(
            c["outcome"] for c in chains.values() if c["outcome"])),
        "attempts_recorded": sum(len(c["attempts"])
                                 for c in chains.values()),
        "retried": sum(1 for c in chains.values()
                       if (c["retries"] or 0) > 0),
        "seal_violations": seal_violations,
        "attempt_mismatches": sorted(mismatched),
        "incomplete": sorted(tid for tid, c in chains.items()
                             if not c["complete"]),
        "replica_deaths": [{
            "replica": r.get("replica"), "reason": r.get("reason"),
            "flight": r.get("flight"), "t": r.get("t"),
        } for r in transitions if r.get("state") == "dead"],
    }
    # The most-travelled chains (retries, then attempt count): the
    # requests whose causal story is worth reading first.
    ranked = sorted(chains.values(),
                    key=lambda c: (-(c["retries"] or 0),
                                   -len(c["attempts"])))
    out["most_retried"] = [{
        "trace_id": c["trace_id"], "outcome": c["outcome"],
        "retries": c["retries"], "attempts": len(c["attempts"]),
        "replica": c["replica"],
    } for c in ranked[:slow_top] if (c["retries"] or 0) > 0
        or len(c["attempts"]) > 1]
    if end is not None and isinstance(end.get("stats"), dict):
        out["final_stats"] = {
            k: end["stats"].get(k)
            for k in ("accepted", "sealed", "outcomes", "retries_spent")}
    if trace_id is not None:
        out["chain"] = chains.get(trace_id)
        if out["chain"] is None:
            out["chain_missing"] = trace_id
    return out


def export_fleet_spans(records: List[Dict[str, Any]], collector,
                       trace_id: Optional[str] = None) -> int:
    """Cross-process Perfetto lanes from a merged fleet stream: per
    trace, one ROUTER lane (admission → sealed) plus one lane per
    replica attempt, replica-side stages tiled inside the attempt span
    (ISSUE 18). Reconstructed post-hoc from event timestamps — the
    attempt's wall span is its serve-side e2e when a joined
    serve_request exists, else the instant of its attempt record.
    Returns the number of chains exported."""
    import zlib

    _MIN = 1e-7  # perfetto drops 0-duration complete events
    chains = _fleet_chains(records)
    n = 0
    for tid, c in sorted(chains.items()):
        if trace_id is not None and tid != trace_id:
            continue
        ts = [a["t"] for a in c["attempts"]
              if isinstance(a.get("t"), (int, float))]
        if isinstance(c.get("sealed_t"), (int, float)):
            ts.append(c["sealed_t"])
        # Admission approximated by the earliest observable moment:
        # the first attempt's serve-side start when joined, else the
        # first event stamp.
        first = c["attempts"][0] if c["attempts"] else None
        if first is not None and first["serve"] \
                and isinstance(first["serve"].get("t"), (int, float)) \
                and isinstance(first["serve"].get("e2e_s"),
                               (int, float)):
            ts.append(first["serve"]["t"] - first["serve"]["e2e_s"])
        if not ts:
            continue
        t0, t1 = min(ts), max(ts)
        base = zlib.crc32(tid.encode()) & 0x7FFFFFFF
        collector.add(
            f"fleet:{c['path'] or '?'}:{c['outcome'] or '?'}",
            t0, max(t1 - t0, _MIN), 0, tid=base, trace_id=tid,
            retries=c["retries"], status=c["status"])
        for i, a in enumerate(c["attempts"]):
            lane = (base + 1 + (a["attempt"] if isinstance(
                a["attempt"], int) else i)) & 0x7FFFFFFF
            s = a["serve"]
            if s and isinstance(s.get("t"), (int, float)) \
                    and isinstance(s.get("e2e_s"), (int, float)):
                a0, dur = s["t"] - s["e2e_s"], s["e2e_s"]
            elif isinstance(a.get("t"), (int, float)):
                a0, dur = a["t"], _MIN
            else:
                continue
            collector.add(
                f"attempt{a['attempt']}:{a['replica']}:{a['outcome']}",
                a0, max(dur, _MIN), 0, tid=lane, trace_id=tid,
                status=a.get("status"))
            cursor = a0
            for stage, sdur in ((s or {}).get("stages") or {}).items():
                if not isinstance(sdur, (int, float)):
                    continue
                collector.add(stage, cursor, max(sdur, _MIN), 1,
                              tid=lane, trace_id=tid)
                cursor += sdur
            if isinstance(a.get("backoff_s"), (int, float)) \
                    and a["backoff_s"] > 0 \
                    and isinstance(a.get("t"), (int, float)):
                # The wait a retry paid AFTER this failed attempt —
                # rendered on the router lane where the sleep ran.
                collector.add("backoff", a["t"],
                              max(a["backoff_s"], _MIN), 1,
                              tid=base, trace_id=tid)
        n += 1
    return n


def summarize_map(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The `pbt diagnose --map` section: per-shard progress, block
    throughput, re-work across incarnations, quarantine/retry totals
    from a stream's map_* records (ISSUE 14). Optional-input-safe like
    the other summarizers — a stream from a SIGKILLed run (no map_end)
    still summarizes, which is the whole point for this workload."""
    starts = [r for r in records if r["event"] == "map_start"]
    end = next((r for r in reversed(records)
                if r["event"] == "map_end"), None)
    blocks = [r for r in records if r["event"] == "map_block"]
    shard_evs = [r for r in records if r["event"] == "map_shard"]

    # Re-work = committed blocks emitted more than once for the same
    # (shard, block) across ALL incarnations in the file — exactly the
    # chaos drill's bounded-re-work metric (map_block only fires after
    # the cursor advance, so a crashed in-flight block never counts).
    seen = collections.Counter((b["shard"], b["block"]) for b in blocks)
    rework = sum(n - 1 for n in seen.values() if n > 1)

    per_shard: Dict[int, Dict[str, Any]] = {}
    for b in blocks:
        s = per_shard.setdefault(b["shard"], {
            "blocks": 0, "seqs": 0, "quarantined": 0, "retries": 0,
            "last_state": None, "consumed": None, "size": None})
        s["blocks"] += 1
        s["seqs"] += b["n"]
        s["quarantined"] += b.get("quarantined") or 0
        s["retries"] += b.get("retries") or 0
    for ev in shard_evs:  # stream order: the LAST transition wins
        s = per_shard.setdefault(ev["shard"], {
            "blocks": 0, "seqs": 0, "quarantined": 0, "retries": 0,
            "last_state": None, "consumed": None, "size": None})
        s["last_state"] = ev["state"]
        if isinstance(ev.get("size"), int):
            s["size"] = ev["size"]
        if isinstance(ev.get("next"), int):
            s["consumed"] = ev["next"]
    for b in blocks:  # committed coverage trumps transition snapshots
        s = per_shard[b["shard"]]
        if isinstance(b.get("end"), int):
            s["consumed"] = max(s["consumed"] or 0, b["end"])

    rates = sorted(b["seqs_per_s"] for b in blocks
                   if isinstance(b.get("seqs_per_s"), (int, float)))
    out: Dict[str, Any] = {
        "manifest": (starts[-1].get("config") if starts else None),
        "incarnations": len(starts),
        "outcome": (end["outcome"] if end
                    else "unknown (no map_end record — killed?)"),
        "blocks": len(blocks),
        "seqs": sum(b["n"] for b in blocks),
        "quarantined": sum(b.get("quarantined") or 0 for b in blocks),
        "retries": sum(b.get("retries") or 0 for b in blocks),
        "rework_blocks": rework,
        "per_shard": {str(k): v for k, v in sorted(per_shard.items())},
        "throughput": {
            "seqs_per_s_p50": _percentile(rates, 0.50),
            "seqs_per_s_last": rates and blocks[-1].get("seqs_per_s")
            or None,
        },
        "halted_shards": sorted({ev["shard"] for ev in shard_evs
                                 if ev["state"] == "halted"}),
        "failed_shards": sorted({ev["shard"] for ev in shard_evs
                                 if ev["state"] == "failed"}),
    }
    if end is not None and isinstance(end.get("stats"), dict):
        out["final_stats"] = end["stats"]
    return out


def render_map(summary: Dict[str, Any]) -> str:
    """Human-readable mapping section (`pbt diagnose --map`)."""
    lines = ["-- map --"]
    lines.append(f"outcome: {summary['outcome']} "
                 f"({summary['incarnations']} incarnation(s))")
    man = summary.get("manifest")
    if man:
        lines.append(
            f"manifest: corpus {man.get('corpus_n')} over "
            f"{man.get('num_shards')} shard(s), block "
            f"{man.get('block_size')}, rows {man.get('rows_per_batch')}"
            f"x{man.get('seq_len')}, trunk "
            f"{man.get('model_fingerprint')}")
    lines.append(
        f"committed: {summary['blocks']} block(s), {summary['seqs']} "
        f"sequence(s), {summary['quarantined']} quarantined, "
        f"{summary['retries']} retry(ies), "
        f"{summary['rework_blocks']} re-worked block(s) across "
        "incarnations")
    tp = summary["throughput"]
    if tp["seqs_per_s_p50"] is not None:
        lines.append(f"throughput: p50 {tp['seqs_per_s_p50']:.2f} "
                     f"seqs/s (last block "
                     f"{tp['seqs_per_s_last'] or 0:.2f})")
    for shard, s in summary["per_shard"].items():
        prog = ""
        if s["size"]:
            done = s["consumed"] if s["consumed"] is not None else 0
            prog = f" {done}/{s['size']}"
        lines.append(
            f"  shard {shard}: {s['blocks']} block(s), {s['seqs']} "
            f"seq(s){prog}, state {s['last_state'] or '?'}"
            + (f", {s['quarantined']} quarantined"
               if s["quarantined"] else "")
            + (f", {s['retries']} retries" if s["retries"] else ""))
    for which in ("halted_shards", "failed_shards"):
        if summary[which]:
            lines.append(f"{which.replace('_', ' ')}: "
                         f"{summary[which]} — see the flight dump / "
                         "shard events")
    return "\n".join(lines)


def render_serve(summary: Dict[str, Any]) -> str:
    """Human-readable serve section (`pbt diagnose --serve`)."""
    lines = ["-- serve --"]
    lines.append(f"outcome: {summary['outcome']}")
    man = summary.get("manifest")
    if man:
        lines.append(
            f"manifest: buckets {man.get('buckets')} classes "
            f"{man.get('batch_classes')} queue {man.get('queue_depth')} "
            f"cache {man.get('cache_size')} trace_rate "
            f"{man.get('trace_sample_rate')}")
    if summary["outcomes"]:
        lines.append("traced requests: " + ", ".join(
            f"{k}={v}" for k, v in sorted(summary["outcomes"].items())))
    e2e = summary["e2e"]
    if e2e["n"]:
        lines.append(f"e2e latency (n={e2e['n']}): "
                     f"p50 {e2e['p50_s'] * 1e3:.2f}ms "
                     f"p99 {e2e['p99_s'] * 1e3:.2f}ms "
                     f"max {e2e['max_s'] * 1e3:.2f}ms")
    attr = summary["stage_attribution"]
    if attr:
        lines.append("where the time went (all traced requests):")
        for stage, a in attr.items():
            share = (f"{100 * a['share']:5.1f}%" if a["share"] is not None
                     else "     ")
            lines.append(f"  {stage:<24} {a['total_s']:10.4f}s {share}")
    for s in summary["slowest"]:
        lines.append(
            f"  slow: {s['request_id']} {s['kind']} {s['outcome']} "
            f"{s['e2e_s'] * 1e3:.2f}ms (mostly {s['dominant_stage']}, "
            f"L={s['bucket_len']} cls={s['batch_class']})")
    per_head = summary.get("per_head") or {}
    if per_head:
        lines.append("per-head (multi-tenant predict_task traffic):")
        for hid, h in per_head.items():
            p50 = f"{h['p50_s'] * 1e3:.2f}ms" if h["p50_s"] is not None \
                else "n/a"
            p99 = f"{h['p99_s'] * 1e3:.2f}ms" if h["p99_s"] is not None \
                else "n/a"
            outc = ", ".join(f"{k}={v}"
                             for k, v in sorted(h["outcomes"].items()))
            lines.append(f"  head {hid}: n={h['n']} p50 {p50} p99 {p99} "
                         f"errors={h['errors']} ({outc})")
    for hid, n in sorted((summary.get("unknown_head_rejects")
                          or {}).items()):
        lines.append(f"  unknown-head rejects: {hid} x{n}")
    rej = summary["rejects"]
    if rej["total"]:
        lines.append(
            f"rejects: {rej['total']} " + ", ".join(
                f"{k}={v}" for k, v in sorted(rej["by_reason"].items()))
            + (f" (queue depth mean {rej['queue_depth_mean']}"
               f" max {rej['queue_depth_max']})"
               if rej["queue_depth_max"] is not None else ""))
    b = summary["batches"]
    if b["n"]:
        lines.append(f"batches: {b['n']} ({b['rows']} rows, mean "
                     f"{b['mean_rows']}/batch, occupancy "
                     f"{b['mean_occupancy']}, pad fraction "
                     f"{b['mean_pad_fraction']})")
        if b.get("segments"):
            lines.append(
                f"  packed: {b['segments']} segments, "
                f"{b['mean_segments_per_row']} per row "
                f"(modes: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(b["modes"].items()))
                + ")")
    ex = summary.get("executables")
    if ex and ex.get("count") is not None:
        lines.append(
            f"executables: {ex['count']} warm "
            f"(mode {ex.get('serve_mode')}, warmup "
            f"{ex.get('warmup_seconds')}s)")
        for stats_key, label in (("fused_path", "fused-kernel"),
                                 ("attention_path", "attention-kernel"),
                                 ("onepass_path", "one-pass-trunk")):
            cov = ex.get(stats_key) or {}
            if not cov:
                continue
            pallas = sum(n for k, n in cov.items()
                         if k.startswith("pallas/"))
            ref = sum(n for k, n in cov.items()
                      if k.startswith("reference/"))
            lines.append(
                f"  {label} coverage: {pallas} executable(s) on "
                f"the Pallas fast path, {ref} on the XLA reference")
            for key, n in sorted(cov.items()):
                lines.append(f"    {key}: {n}")
        fp = ex.get("fused_path") or {}
        if not fp:
            # Pre-ISSUE-10 stats snapshots: one-sided fallback view.
            fb = ex.get("fused_fallback") or {}
            for reason, n in sorted(fb.items()):
                lines.append(f"  fused-kernel fallback ({reason}): "
                             f"{n} executable(s) on the XLA reference "
                             "path")
    nb = summary.get("neighbors")
    if nb:
        outc = ", ".join(f"{k}={v}"
                         for k, v in sorted(nb["outcomes"].items()))
        hit = (f", cache hit rate {nb['cache_hit_rate']}"
               if nb["cache_hit_rate"] is not None else "")
        lines.append(f"neighbors: {nb['requests_traced']} traced "
                     f"({outc}{hit})")
        el, ll = nb["embed_leg"], nb["lookup_leg"]
        if ll["n"]:
            lines.append(
                f"  embed leg: p50 {el['p50_s'] * 1e3:.2f}ms "
                f"p99 {el['p99_s'] * 1e3:.2f}ms; lookup leg: "
                f"p50 {ll['p50_s'] * 1e3:.2f}ms "
                f"p99 {ll['p99_s'] * 1e3:.2f}ms (n={ll['n']})")
        if nb.get("mean_lookup_s") is not None:
            lines.append(
                f"  probes: {nb['queries']} sampled, mean lookup "
                f"{nb['mean_lookup_s'] * 1e3:.2f}ms over "
                f"{nb['mean_candidates']} candidate(s)")
        fin = nb.get("final")
        if fin:
            lines.append(
                f"  index: {fin.get('num_vectors')} vector(s), "
                f"nprobe {fin.get('nprobe')}, "
                f"{fin.get('lookup_executables')} warm lookup "
                f"executable(s), identity "
                f"{str(fin.get('index_digest'))[:16]}…")
    for br in summary["slo_breaches"]:
        lines.append(f"SLO BREACH: {br['objective']} burn "
                     f"{br['burn_rate']:.2f} ({br['bad']}/{br['total']} "
                     f"bad) at t={br['t']:.2f}")
    if not summary["slo_breaches"] and summary.get("final_slo"):
        lines.append("slo: no breach events; final burn rates: " + ", ".join(
            f"{k}={v.get('burn_rate')}"
            for k, v in summary["final_slo"].items()))
    return "\n".join(lines)


def _render_chain(c: Dict[str, Any]) -> List[str]:
    """One trace's causal chain, admission → attempts → sealed."""
    lines = [f"trace {c['trace_id']}: {c['path'] or '?'} "
             f"{c['outcome'] or 'UNSEALED'}"
             + ("" if c["complete"] else "  [INCOMPLETE CHAIN]")]
    lines.append(f"  admission → router (trace {c['trace_id']})")
    for a in c["attempts"]:
        status = f" status {a['status']}" if a.get("status") is not None \
            else ""
        lines.append(f"  attempt {a['attempt']}: replica "
                     f"{a['replica']} {a['outcome']}{status}")
        s = a.get("serve")
        if s:
            stages = s.get("stages") or {}
            tile = " | ".join(f"{k} {v * 1e3:.2f}ms"
                              for k, v in stages.items()
                              if isinstance(v, (int, float)))
            e2e = (f"{s['e2e_s'] * 1e3:.2f}ms"
                   if isinstance(s.get("e2e_s"), (int, float)) else "?")
            lines.append(f"    replica trace {s.get('request_id')} "
                         f"{s.get('outcome')} e2e {e2e}"
                         + (f": {tile}" if tile else ""))
        if isinstance(a.get("backoff_s"), (int, float)) \
                and a["backoff_s"] > 0:
            lines.append(f"  backoff {a['backoff_s'] * 1e3:.1f}ms")
    seal = f"  sealed: {c['outcome'] or '?'}"
    if c.get("status") is not None:
        seal += f" status {c['status']}"
    if c.get("retries") is not None:
        seal += f" after {c['retries']} retry(ies)"
    if c["seals"] != 1:
        seal += f"  [sealed {c['seals']}x — exactly-once VIOLATED]"
    lines.append(seal)
    return lines


def render_fleet(summary: Dict[str, Any]) -> str:
    """Human-readable fleet section (`pbt diagnose --fleet`)."""
    lines = ["-- fleet --"]
    lines.append(f"outcome: {summary['outcome']}")
    man = summary.get("manifest")
    if man:
        reps = man.get("replicas") or {}
        lines.append(
            f"manifest: {len(reps)} replica(s) "
            f"{sorted(reps)} max_retries {man.get('max_retries')} "
            f"budget floor {man.get('retry_budget_floor')} "
            f"ratio {man.get('retry_budget_ratio')}")
    if summary["outcomes"]:
        lines.append(
            f"traces: {summary['traces']} sealed — " + ", ".join(
                f"{k}={v}" for k, v in sorted(summary["outcomes"].items()))
            + f"; {summary['attempts_recorded']} attempt(s) recorded, "
            f"{summary['retried']} trace(s) retried")
    for tid, n in sorted(summary["seal_violations"].items()):
        lines.append(f"  SEAL VIOLATION: trace {tid} sealed {n}x "
                     "(exactly-once broken)")
    for tid in summary["attempt_mismatches"]:
        lines.append(f"  ATTEMPT MISMATCH: trace {tid} — attempts on "
                     "record != retries spent + 1")
    inc = [t for t in summary["incomplete"]
           if t not in summary["seal_violations"]
           and t not in summary["attempt_mismatches"]]
    if inc:
        lines.append(f"incomplete chains: {len(inc)} "
                     f"(e.g. {inc[:3]})")
    for d in summary["replica_deaths"]:
        flight = f", flight dump {d['flight']}" if d.get("flight") \
            else ""
        lines.append(f"replica DEATH: {d['replica']} "
                     f"({d['reason']}){flight}")
    for m in summary.get("most_retried") or []:
        lines.append(
            f"  retried: {m['trace_id']} {m['outcome']} — "
            f"{m['attempts']} attempt(s), {m['retries']} retry(ies), "
            f"final replica {m['replica']}")
    fin = summary.get("final_stats")
    if fin:
        lines.append(
            f"router: accepted {fin.get('accepted')} sealed "
            f"{fin.get('sealed')} retries_spent "
            f"{fin.get('retries_spent')}")
    chain = summary.get("chain")
    if chain:
        lines.append("")
        lines.extend(_render_chain(chain))
    elif summary.get("chain_missing"):
        lines.append(f"trace {summary['chain_missing']}: NOT FOUND in "
                     "this stream")
    return "\n".join(lines)


def render(summary: Dict[str, Any]) -> str:
    """Human-readable report (the `pbt diagnose` default output)."""
    lines = []
    lines.append(f"outcome: {summary['outcome']}")
    if summary.get("incarnations", 1) > 1:
        lines.append(f"requeued stream: {summary['incarnations']} "
                     "incarnations in this file (rates cover the last)")
    man = summary.get("manifest")
    if man:
        lines.append(
            f"manifest: jax {man.get('jax_version')} pid {man.get('pid')}"
            f" mesh {man.get('mesh')} chips {man.get('n_chips')}"
            + (" (resumed)" if man.get("resumed") else ""))
    lines.append("events: " + ", ".join(
        f"{k}={v}" for k, v in sorted(summary["counts"].items())))
    rate = summary["step_rate"]
    sps = rate.get("steps_per_sec")
    lines.append(
        "step rate: "
        + (f"{sps:.4f} steps/s (StepTimer cumulative)" if sps is not None
           else "n/a")
        + (f", {rate['stream_steps_per_sec']:.4f} steps/s (stream wall"
           f"-clock)" if "stream_steps_per_sec" in rate else "")
        + (f" — trend {rate['trend']}" if "trend" in rate else ""))
    if summary["stalls"]:
        lines.append("slowest windows (window_step_ms, ckpt_in_flight):")
        for s in summary["stalls"]:
            lines.append(f"  step {s['step']:>8}: {s['window_step_ms']:10.2f}"
                         f" ms {'[ckpt]' if s['ckpt_in_flight'] else ''}")
    b = summary["boundary"]
    ratio = b.get("overlap_ratio")
    lines.append(
        f"boundary: {b['ckpt_stages_landed']} staged saves landed, "
        f"{b['overlap_s']:.3f}s overlapped"
        + (f" ({100 * ratio:.2f}% of {b['wall_s']:.1f}s wall)"
           if ratio is not None else "")
        + f", {b['evals']} evals")
    fl = summary.get("flight")
    if fl:
        lines.append(f"flight dump: reason={fl['reason']} pid={fl['pid']} "
                     f"({fl['events']} events)")
    lines.append(f"last {len(summary['last_events'])} events before end:")
    for r in summary["last_events"]:
        extra = " ".join(f"{k}={v}" for k, v in r.items()
                         if k not in ("event", "step", "t") and v is not None)
        lines.append(f"  t={r['t']:.2f} {r['event']:<11}"
                     f" step={r.get('step')} {extra}".rstrip())
    return "\n".join(lines)
