"""Unified telemetry subsystem (ISSUE 3 tentpole).

Four cooperating pieces behind one `Telemetry` facade:

- **events** — append-only, schema-versioned JSONL run events
  (`run_start`, `step`, `ckpt_stage`, `eval`, `requeue`, `nan_halt`,
  `run_end`, `note`) with crash-safe line-buffered writes;
- **metrics** — a labeled counter/gauge/histogram registry absorbing
  StepTimer summaries, overlap accounting, ZeRO per-chip state bytes,
  data-pipeline wait time, and host RSS/HBM estimates; exports JSONL
  snapshots and a Prometheus-style textfile;
- **tracing** — nested host `span()`s that forward to
  jax.profiler.TraceAnnotation when a device trace is live and dump
  Perfetto-compatible trace-event JSON;
- **flight** — a bounded ring of the last N event records, dumped to
  `flight_<pid>.json` on SIGTERM / NaN-halt / unhandled exception.

Consumers: `pbt diagnose` (obs/diagnose.py), `tools/validate_events.py`,
`tools/trace_attribution.py` (span dumps share the device-trace
format), `tools/tpu_watch.py` and `bench.py` (note events on the same
stream). docs/observability.md documents the schema and conventions.

Overhead contract: `NULL` (the default when no telemetry is passed) is
a do-nothing facade — `emit` returns None, `span` is a shared
nullcontext, `metrics` is a disabled registry — so instrumented code
paths cost ~zero when telemetry is off.

No jax import at module level: the whole package must be usable on a
machine that only holds the artifacts.
"""

from __future__ import annotations

import contextlib
import os
import threading as _threading
import time
from typing import Any, Dict, Optional

from proteinbert_tpu.obs.events import (
    CKPT_PHASES, EVENT_FIELDS, FLEET_REPLICA_STATES,
    FLEET_REQUEST_OUTCOMES, INDEX_BUILD_STATES, INDEX_SHARD_STATES,
    MAP_OUTCOMES, MAP_SHARD_STATES, OUTCOMES,
    SCHEMA_VERSION,
    SERVE_OUTCOMES, SERVE_REJECT_REASONS, SERVE_REQUEST_OUTCOMES,
    EventLog,
    build_record, make_example, make_record, read_events, sanitize,
    validate_record,
)
from proteinbert_tpu.obs.flight import (
    FlightRecorder, flight_path, validate_flight_dump,
)
from proteinbert_tpu.obs.metrics import MetricsRegistry, QuantileWindow
from proteinbert_tpu.obs.slo import (
    ExemplarHistogram, ProfileTrigger, SLObjective, SLOEvaluator,
    parse_slo, parse_slos,
)
from proteinbert_tpu.obs.tracing import SpanCollector, span

_NULL_CTX = contextlib.nullcontext()


class Telemetry:
    """Bundle of event log + metrics registry + flight recorder +
    optional span collector, with one `emit()` that feeds both the
    durable stream and the crash ring."""

    enabled = True

    def __init__(
        self,
        events_path: Optional[str] = None,
        metrics: bool = True,
        flight_capacity: int = 256,
        flight_dir: Optional[str] = None,
        spans: bool = False,
    ):
        self.events = EventLog(events_path) if events_path else None
        self.metrics = MetricsRegistry(enabled=metrics)
        if flight_dir is None:
            flight_dir = (os.path.dirname(os.path.abspath(events_path))
                          if events_path else ".")
        self.flight = FlightRecorder(capacity=flight_capacity,
                                     directory=flight_dir)
        self.spans = SpanCollector() if spans else None
        self._seq = 0          # guarded-by: _lock
        self._last_t = 0.0     # guarded-by: _lock
        self._lock = _threading.Lock()

    def emit(self, event: str, **fields) -> Optional[Dict[str, Any]]:
        """Append one event record to the JSONL stream (when configured)
        AND to the flight ring. Never raises."""
        if self.events is not None:
            rec = self.events.emit(event, **fields)
        else:
            # Flight/metrics-only mode: the SAME construction contract
            # as the EventLog path (shared build_record: validation +
            # never-raises), with its own locked seq (the checkpoint
            # stager thread emits concurrently) and clamped t.
            with self._lock:
                t = max(time.time(), self._last_t)
                self._last_t = t
                rec = build_record(event, self._seq, t, fields)
                if rec is not None:
                    self._seq += 1
        if rec is not None:
            self.flight.record(rec)
        return rec

    def span(self, name: str, step: Optional[int] = None, **args):
        return span(name, collector=self.spans, step=step, **args)

    def dump_flight(self, reason: str) -> Optional[str]:
        return self.flight.dump(reason)

    def close(self) -> None:
        # Deliberately does NOT uninstall a flight excepthook: close()
        # runs in `finally` blocks BEFORE an escaping exception reaches
        # sys.excepthook, and the crash dump must still fire then (the
        # ring and dump path don't depend on the closed event file).
        if self.events is not None:
            self.events.close()


class _NullTelemetry:
    """Do-nothing stand-in: the default when no telemetry is configured.
    All instrumented call sites go through this with ~zero cost."""

    enabled = False
    events = None
    spans = None
    flight = None
    metrics = MetricsRegistry(enabled=False)

    def emit(self, event: str, **fields) -> None:
        return None

    def span(self, name: str, step: Optional[int] = None, **args):
        return _NULL_CTX

    def dump_flight(self, reason: str) -> None:
        return None

    def close(self) -> None:
        pass


NULL = _NullTelemetry()


def as_telemetry(t: Optional[Telemetry]) -> Any:
    """`telemetry or NULL` with an explicit name at every call site."""
    return t if t is not None else NULL


__all__ = [
    "Telemetry", "NULL", "as_telemetry",
    "EventLog", "read_events", "validate_record", "make_record",
    "make_example", "sanitize",
    "SCHEMA_VERSION", "EVENT_FIELDS", "CKPT_PHASES", "OUTCOMES",
    "SERVE_OUTCOMES", "SERVE_REJECT_REASONS", "SERVE_REQUEST_OUTCOMES",
    "FLEET_REPLICA_STATES", "FLEET_REQUEST_OUTCOMES",
    "INDEX_BUILD_STATES", "INDEX_SHARD_STATES",
    "MAP_OUTCOMES", "MAP_SHARD_STATES",
    "MetricsRegistry", "QuantileWindow",
    "SLObjective", "SLOEvaluator", "ExemplarHistogram", "ProfileTrigger",
    "parse_slo", "parse_slos",
    "SpanCollector", "span",
    "FlightRecorder", "flight_path", "validate_flight_dump",
]
