"""Host-side span tracing with device-trace forwarding.

`span("name")` times a nested host region. Three sinks, all optional:

- a SpanCollector accumulates finished spans and dumps them as
  Perfetto-compatible `{"traceEvents": [...]}` JSON — the SAME format
  jax.profiler's trace.json.gz uses, so `tools/trace_attribution.py`
  parses host-span dumps and device traces with one parser;
- when jax is already imported, the span body also runs under
  `jax.profiler.TraceAnnotation`, so spans appear on the host lane of a
  live device trace (and with `step=`, `StepTraceAnnotation` gives the
  profiler step boundaries for its per-step views);
- nesting depth is tracked per-thread, so a collector dump renders as a
  flame graph (perfetto nests by timestamps; depth is kept as an arg
  for flat consumers).

jax is NEVER imported by this module — only used if something else
already did — so the obs package stays importable on artifact-only
machines.
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

_tls = threading.local()


def _depth() -> int:
    return getattr(_tls, "depth", 0)


class SpanCollector:
    """Bounded buffer of finished spans (oldest dropped past capacity —
    a long run must not grow host memory without bound)."""

    def __init__(self, capacity: int = 20000):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # getpid() is a real syscall on every add() — measurably slow
        # under sandboxed kernels (~90us observed) — and the pid cannot
        # change under us: collectors are not expected to survive fork.
        self._pid = os.getpid()

    def add(self, name: str, wall_start: float, dur_s: float,
            depth: int, tid: Optional[int] = None, **args) -> None:
        """Record one finished span. `tid` defaults to the calling
        thread; post-hoc emitters (serve request traces, which replay a
        request's stages after it resolves) pass a synthetic tid so
        each request renders on its own lane — overlapping requests on
        one thread id would nest into nonsense."""
        with self._lock:
            self._spans.append({
                "ph": "X", "name": name, "pid": self._pid,
                "tid": threading.get_ident() if tid is None else tid,
                "ts": round(wall_start * 1e6, 3),   # perfetto: microseconds
                "dur": round(dur_s * 1e6, 3),
                "args": {"depth": depth, **args} if (args or depth)
                        else {"depth": 0},
            })

    def __len__(self) -> int:
        return len(self._spans)

    def to_perfetto(self) -> Dict[str, Any]:
        meta = [{"ph": "M", "name": "process_name", "pid": self._pid,
                 "args": {"name": "proteinbert_tpu host spans"}}]
        with self._lock:
            return {"traceEvents": meta + list(self._spans)}

    def dump(self, path: str) -> str:
        """Write trace-event JSON (gzipped when the path ends in .gz) —
        loadable by ui.perfetto.dev and tools/trace_attribution.py."""
        data = json.dumps(self.to_perfetto())
        if path.endswith(".gz"):
            with gzip.open(path, "wt") as f:
                f.write(data)
        else:
            with open(path, "w") as f:
                f.write(data)
        return path


def _jax_annotation(name: str, step: Optional[int] = None):
    """A TraceAnnotation context when jax is live, else a null context.
    Checked through sys.modules: telemetry must not be the thing that
    pays the jax import."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        if step is not None:
            return jax.profiler.StepTraceAnnotation(name, step_num=step)
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def span(name: str, collector: Optional[SpanCollector] = None,
         step: Optional[int] = None, **args):
    """Nested host span: times the body, forwards to the jax profiler
    when available, records into `collector` when given."""
    depth = _depth()
    _tls.depth = depth + 1
    wall0 = time.time()
    t0 = time.perf_counter()
    try:
        with _jax_annotation(name, step):
            yield
    finally:
        _tls.depth = depth
        if collector is not None:
            dur = time.perf_counter() - t0
            if step is not None:
                args["step"] = step
            collector.add(name, wall0, dur, depth, **args)

# (A step_span(step, …) convenience wrapper used to live here; nothing
# referenced it — removed by the ISSUE 15 dead-export sweep. Pass
# `step=` to span() for StepTraceAnnotation boundaries.)
