"""Metrics registry: labeled counters / gauges / histograms, one sink.

Absorbs the host-timer aggregation previously scattered across
`utils/profiling.Profiler`, StepTimer's summary dicts, ZeRO's comm/HBM
accounting, and the data-pipeline wait counters: producers register
instruments here; consumers read ONE snapshot (JSON) or a
Prometheus-style textfile instead of N private formats.

Overhead contract: a DISABLED registry hands out shared null
instruments whose methods are constant no-ops — no dict lookups, no
perf_counter calls — so the hot step path pays ~zero when telemetry is
off, and the enabled path only does O(1) float arithmetic per
observation (the trainer additionally confines its observations to the
log cadence, keeping measured overhead under 1% of step time).

Stdlib-only; no jax import (tools must run anywhere).
"""

from __future__ import annotations

import collections
import contextlib
import os
import tempfile
import threading
import time
from typing import Any, Dict, Optional


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Streaming summary (count/sum/min/max/last): enough for rates and
    stall detection without per-observation allocation; exported in
    Prometheus summary style (_count/_sum plus min/max gauges)."""

    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.last = v


def nearest_rank(sorted_values, fraction: float) -> Optional[float]:
    """Nearest-rank pick from an ASCENDING list; `fraction` in [0, 1].
    The one percentile convention for the obs package (QuantileWindow,
    diagnose): a rank-rule change happens here or nowhere."""
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1,
              max(0, int(round(fraction * (len(sorted_values) - 1)))))
    return sorted_values[idx]


class QuantileWindow:
    """Bounded ring of recent observations with percentile reads — the
    p50/p99 a streaming Histogram cannot provide (count/sum/min/max
    only). Previously `serve/server._LatencyWindow`; it lives in the
    registry now so `/metrics`, `Server.stats()`, and `serve_request`
    events all read the SAME ring and cannot drift (percentiles are
    computed at read time, never cached).

    Thread-safe: serving observes from the scheduler thread while
    stats()/scrapes read from client/HTTP threads."""

    __slots__ = ("_ring", "_lock")

    def __init__(self, capacity: int = 2048):
        self._ring: "collections.deque[float]" = collections.deque(
            maxlen=capacity)               # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring.append(float(seconds))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def values(self):
        """A consistent copy of the raw ring, oldest first. The fleet
        aggregation plane (ISSUE 18) merges percentile windows across
        replicas by CONCATENATING raw values — a fleet p99 is not any
        function of per-replica p99s — so the scrape endpoint ships
        these, not summary()."""
        with self._lock:
            return list(self._ring)

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile; `q` is in PERCENT (0–100), e.g.
        `percentile(99)` — not the 0–1 fraction `summary()` uses
        internally. None while the ring is empty."""
        with self._lock:
            data = sorted(self._ring)
        return nearest_rank(data, q / 100.0)

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:
            if not self._ring:
                return {"n": 0, "p50_s": None, "p99_s": None, "mean_s": None}
            data = sorted(self._ring)
        return {"n": len(data),
                "p50_s": round(nearest_rank(data, 0.50), 6),
                "p99_s": round(nearest_rank(data, 0.99), 6),
                "mean_s": round(sum(data) / len(data), 6)}


class _NullInstrument:
    """Shared do-nothing counter/gauge/histogram for a disabled registry."""

    __slots__ = ()
    value = 0.0
    count = 0
    total = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL = _NullInstrument()
_NULL_CTX = contextlib.nullcontext()


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._windows: Dict[str, QuantileWindow] = {}

    # ----------------------------------------------------- instruments

    def counter(self, name: str, **labels) -> Counter:
        if not self.enabled:
            return _NULL
        return self._get(self._counters, Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        if not self.enabled:
            return _NULL
        return self._get(self._gauges, Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        if not self.enabled:
            return _NULL
        return self._get(self._histograms, Histogram, name, labels)

    def quantile_window(self, name: str, capacity: int = 2048,
                        **labels) -> QuantileWindow:
        """A registered percentile ring (exported as `<name>_p50_s` /
        `_p99_s` / `_mean_s` gauge families plus `<name>_window_n`).

        Unlike the other instruments, a DISABLED registry returns a
        live but UNREGISTERED window rather than a shared no-op: the
        callers that need percentiles (Server.stats) must report real
        numbers even under the NULL telemetry facade, and a deque
        append is cheap enough to keep the ~zero-overhead contract."""
        if not self.enabled:
            return QuantileWindow(capacity)
        k = _key(name, labels)
        win = self._windows.get(k)
        if win is None:
            win = self._windows[k] = QuantileWindow(capacity)
        return win

    def _get(self, table, cls, name, labels):
        k = _key(name, labels)
        inst = table.get(k)
        if inst is None:
            inst = table[k] = cls()
        return inst

    @contextlib.contextmanager
    def _timed(self, hist: Histogram):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            hist.observe(time.perf_counter() - t0)

    def timer(self, name: str, **labels):
        """`with registry.timer("phase"):` — observes elapsed seconds
        into histogram `name`. Free (no clock reads) when disabled."""
        if not self.enabled:
            return _NULL_CTX
        return self._timed(self._get(self._histograms, Histogram,
                                     name, labels))

    def set_many(self, values: Dict[str, float], prefix: str = "") -> None:
        """Bulk gauge update from a metrics dict (e.g. a StepTimer
        summary); non-numeric values are skipped."""
        if not self.enabled:
            return
        for k, v in values.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(prefix + k).set(v)

    # ----------------------------------------------------- export

    def snapshot(self) -> Dict[str, Any]:
        out = {
            "counters": {k: c.value for k, c in self._counters.items()},
            "gauges": {k: g.value for k, g in self._gauges.items()},
            "histograms": {
                k: {"count": h.count, "sum": h.total,
                    "min": (h.min if h.count else None),
                    "max": (h.max if h.count else None),
                    "mean": (h.total / h.count if h.count else None),
                    "last": (h.last if h.count else None)}
                for k, h in self._histograms.items()
            },
        }
        if self._windows:
            out["windows"] = {k: w.summary()
                              for k, w in self._windows.items()}
        return out

    def window_values(self) -> Dict[str, Any]:
        """{window key: raw ring values} — the machine-readable form
        `/metrics.json` ships so a fleet router can merge percentiles
        across replicas from the concatenated observations."""
        return {k: w.values() for k, w in self._windows.items()}

    def write_snapshot(self, path: str) -> None:
        """Append one timestamped JSONL snapshot line."""
        import json

        with open(path, "a", buffering=1) as f:
            f.write(json.dumps({"t": round(time.time(), 3),
                                **self.snapshot()}) + "\n")

    def prometheus_text(self, prefix: str = "pbt_") -> str:
        """Prometheus textfile-collector exposition (counters as
        counter, gauges as gauge, histograms as summary-style
        _count/_sum plus _min/_max gauges)."""
        lines = []
        typed = set()

        def metric(key, suffix, kind, value):
            # TYPE lines are per SAMPLE FAMILY (bare name + suffix,
            # labels stripped): a labeled histogram 'h{l="x"}' exports
            # families pbt_h_count/_sum/_min/_max, each typed once —
            # never a TYPE line for a family with no samples.
            name, _, labels = key.partition("{")
            family = f"{prefix}{name}{suffix}"
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} {kind}")
            labels = ("{" + labels) if labels else ""
            lines.append(f"{family}{labels} {value:.9g}")

        for k, c in sorted(self._counters.items()):
            metric(k, "", "counter", c.value)
        for k, g in sorted(self._gauges.items()):
            metric(k, "", "gauge", g.value)
        for k, h in sorted(self._histograms.items()):
            metric(k, "_count", "counter", h.count)
            metric(k, "_sum", "counter", h.total)
            if h.count:
                metric(k, "_min", "gauge", h.min)
                metric(k, "_max", "gauge", h.max)
        for k, w in sorted(self._windows.items()):
            # Percentiles computed at scrape time from the live ring —
            # the exposition can never lag what stats() reports.
            s = w.summary()
            metric(k, "_window_n", "gauge", s["n"])
            if s["n"]:
                metric(k, "_p50_s", "gauge", s["p50_s"])
                metric(k, "_p99_s", "gauge", s["p99_s"])
                metric(k, "_mean_s", "gauge", s["mean_s"])
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str, prefix: str = "pbt_") -> None:
        """Atomic write (tmp + rename): a scraper must never read a
        half-written textfile."""
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".prom.", dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(self.prometheus_text(prefix))
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # ------------------------------------------- Profiler-compat view

    def timer_summary(self) -> Dict[str, Dict[str, float]]:
        """The aggregation `utils/profiling.Profiler.summary()` used to
        build — {name: {total_s, count, mean_s}} over timer histograms —
        so Profiler can be a thin shim over this registry."""
        return {
            k: {"total_s": h.total, "count": h.count,
                "mean_s": h.total / h.count}
            for k, h in self._histograms.items() if h.count
        }
