"""Crash-forensics flight recorder: last-N events, dumped on death.

A bounded ring buffer holds the most recent event records (every record
the Telemetry facade emits lands here, whether or not an events file is
configured). On SIGTERM, NaN-halt, or an unhandled exception the buffer
is dumped to `flight_<pid>.json` so every death leaves forensics — the
event sequence right before the end, which a truncated text log rarely
captures (staged-checkpoint in flight? eval pending? what were the last
window rates?).

Dump rules:
- atomic (tmp + rename): the reader never sees a torn dump;
- NEVER raises: the original failure (the signal, the NaN, the
  exception) must stay the reported cause of death — a full disk on the
  way down is logged and swallowed;
- repeated dumps overwrite: the LAST picture before death wins (a
  signal-time dump followed by the cleaner preemption-path dump).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from proteinbert_tpu.obs.events import SCHEMA_VERSION, sanitize

logger = logging.getLogger(__name__)


def flight_path(directory: str, pid: Optional[int] = None) -> str:
    return os.path.join(directory, f"flight_{pid or os.getpid()}.json")


class FlightRecorder:
    def __init__(self, capacity: int = 256, directory: str = "."):
        self.capacity = capacity
        self.directory = os.path.abspath(directory)
        # RLock, not Lock: dump() runs inside the SIGTERM handler, which
        # Python executes on the MAIN thread between bytecodes — if the
        # signal lands while that same thread is inside record()'s lock,
        # a non-reentrant lock would deadlock the clean-preemption path.
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.RLock()
        self._prev_excepthook = None

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the ring to `flight_<pid>.json`; returns the path, or
        None on failure (logged, never raised)."""
        path = path or flight_path(self.directory)
        payload = {
            "v": SCHEMA_VERSION,
            "kind": "flight_recorder",
            "pid": os.getpid(),
            "reason": str(reason),
            "dumped_at": round(time.time(), 3),
            "capacity": self.capacity,
            "events": sanitize(self.snapshot()),
        }
        try:
            d = os.path.dirname(path) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(prefix=".flight.", dir=d)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        except OSError:
            logger.warning("flight-recorder dump to %s failed", path,
                           exc_info=True)
            return None
        logger.warning("flight recorder dumped %d events to %s (%s)",
                       len(payload["events"]), path, reason)
        return path

    # ------------------------------------------------- crash hooks

    def install_excepthook(self) -> None:
        """Dump on any unhandled exception, then defer to the previous
        hook — so the traceback still prints and a prior hook (pytest,
        a supervisor) still runs."""
        if self._prev_excepthook is not None:
            return  # already installed
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.dump(f"unhandled_{exc_type.__name__}")
            self._prev_excepthook(exc_type, exc, tb)

        sys.excepthook = hook

    def uninstall_excepthook(self) -> None:
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None


def validate_flight_dump(payload: Any) -> None:
    """Raise ValueError unless `payload` is a well-formed flight dump
    (shared by tools/validate_events.py and the tests)."""
    from proteinbert_tpu.obs.events import validate_record

    if not isinstance(payload, dict):
        raise ValueError("flight dump is not an object")
    if payload.get("kind") != "flight_recorder":
        raise ValueError(f"kind {payload.get('kind')!r} != 'flight_recorder'")
    if payload.get("v") != SCHEMA_VERSION:
        raise ValueError(
            f"schema version {payload.get('v')!r} != {SCHEMA_VERSION}")
    for field, typ in (("pid", int), ("reason", str),
                      ("dumped_at", (int, float)), ("events", list)):
        if not isinstance(payload.get(field), typ):
            raise ValueError(f"missing/mistyped field {field!r}")
    for i, rec in enumerate(payload["events"]):
        try:
            validate_record(rec)
        except ValueError as e:
            raise ValueError(f"events[{i}]: {e}") from None
