"""Structured run-event log: append-only, schema-versioned JSONL.

One stream per run, one JSON object per line. Every record carries the
schema version (`v`), the event type (`event`), a per-process monotonic
sequence number (`seq`), and a monotonically non-decreasing wall-clock
stamp (`t`) — so a reader can order records even across a torn tail and
correlate them with external logs. Writes are line-buffered appends: a
crash loses at most the partially-written last line, never an earlier
record, and `read_events` skips a torn tail instead of dying on it.

This module is deliberately stdlib-only (no jax import): the schema
validator (`tools/validate_events.py`) and `pbt diagnose` must work on
machines that only hold the artifacts.

Event types and their required payload fields are in EVENT_FIELDS;
`validate_record` is the single source of truth the writer, the
validator tool, and the tier-1 round-trip test all share.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1

# Per-type REQUIRED payload fields (name -> type or tuple of types).
# Extra fields are always allowed — the schema bounds the floor, not the
# ceiling, so emitters can attach context without a schema bump.
EVENT_FIELDS: Dict[str, Dict[str, Any]] = {
    # Run manifest: everything needed to interpret the rest of the
    # stream without the shell history (config, mesh, jax version).
    "run_start": {"config": dict, "jax_version": str, "pid": int},
    # One per log cadence; `metrics` is the logged metrics dict
    # (loss/acc + StepTimer summary incl. window_* rates).
    "step": {"step": int, "metrics": dict},
    # Checkpoint boundary lifecycle: phase in CKPT_PHASES.
    "ckpt_stage": {"step": int, "phase": str},
    # One per eval bracket (sync or overlap-resolved — same payload).
    "eval": {"step": int, "metrics": dict},
    # Preemption (SIGTERM/SIGINT): the run exits 75 for a supervisor.
    "requeue": {"step": int, "reason": str},
    # Non-finite loss/grad watch fired (on_nan halt or warn).
    "nan_halt": {"step": int, "metrics": dict},
    # Terminal record; outcome in OUTCOMES, perf is StepTimer.summary().
    "run_end": {"outcome": str, "perf": dict},
    # Generic annotated event for tools (tpu_watch, bench) that share
    # the stream format without being training runs.
    "note": {"source": str},
    # ---- online serving lifecycle (proteinbert_tpu/serve/) ----
    # Server manifest: serving config (buckets, batch classes, queue
    # depth, cache size) — the serving counterpart of run_start.
    "serve_start": {"config": dict, "pid": int},
    # One per dispatched micro-batch: which compiled shape class ran and
    # how full it was (rows ≤ the padded batch class size). Ragged
    # packed batches (ISSUE 9) additionally carry `mode` ("ragged"),
    # `segments` (requests packed into the batch), `segments_per_row`,
    # and `pad_fraction` of the fixed (rows, seq_len) grid — typed
    # below when present.
    "serve_batch": {"kind": str, "bucket_len": int, "rows": int},
    # One per rejected request: reason in SERVE_REJECT_REASONS
    # (+ queue_depth at rejection time, when the emitter knows it).
    "serve_reject": {"reason": str},
    # Terminal serving record; outcome in SERVE_OUTCOMES, stats is
    # Server.stats() (requests/rejections/cache hit rate/latency).
    "serve_end": {"outcome": str, "stats": dict},
    # ---- per-request serve tracing + SLOs (ISSUE 6) ----
    # One per SAMPLED (or failed/rejected — always sampled) request:
    # the request's stage-duration breakdown. `stages` maps stage name
    # (submit/queue/batch_form/dispatch/execute/finalize) → seconds;
    # stages are contiguous clock intervals, so their sum equals e2e_s
    # up to float rounding. outcome in SERVE_REQUEST_OUTCOMES. Extra
    # fields: e2e_s, bucket_len, batch_class, rows, pad_fraction,
    # cache, sampled, error.
    "serve_request": {"kind": str, "outcome": str, "request_id": str,
                      "stages": dict},
    # An SLO objective's burn rate crossed 1.0 (error budget burning
    # faster than it accrues). Extra fields: window_s, bad, total,
    # bad_fraction, attribution, profile_path.
    "slo_breach": {"objective": str, "burn_rate": (int, float)},
    # ---- multi-tenant head registry (ISSUE 8) ----
    # A finetuned head landed in the registry (train/finetune.finetune
    # with registry=, or `pbt finetune --register-head`). `kind` is the
    # TaskConfig kind. Extra fields: name, trunk_fingerprint, metrics.
    "head_registered": {"head_id": str, "kind": str},
    # One downstream-task eval of a registered head (heads/eval.py,
    # `pbt eval-heads`, bench.py --heads). `metrics` carries the
    # per-task numbers (per_residue_accuracy / accuracy+auc_proxy /
    # spearman+mse) plus a normalized `score` — the series the bench-
    # trajectory sentinel fits so finetune-quality regressions gate
    # like perf does. Extra fields: kind, name.
    "head_eval": {"head_id": str, "metrics": dict},
    # ---- elastic topology (ISSUE 11) ----
    # One checkpoint resharded onto a new mesh layout
    # (parallel/reshard.py, `pbt reshard`). `target_mesh` is the axis
    # dict the state was restored onto ({} = unsharded single device);
    # `wire_bytes` is the collective schedule's per-collective output
    # bytes from the HLO byte-counter (zero.collective_bytes_from_hlo),
    # or {"total": 0} with schedule="host_staged" when source and
    # target device sets differ and the move goes through the host.
    # Extra fields: source_mesh, zero_update, schedule, parity, src,
    # dst.
    "reshard": {"step": int, "target_mesh": dict, "wire_bytes": dict},
    # ---- serve fleet (ISSUE 11): router in front of N replicas ----
    # Router manifest (replica URLs, retry/health policy) — the fleet
    # counterpart of serve_start.
    "fleet_start": {"config": dict, "pid": int},
    # One replica state transition: state in FLEET_REPLICA_STATES.
    # Extra fields: url, reason, consecutive_failures, burn_rate.
    "fleet_replica": {"replica": str, "state": str},
    # One terminal routed request: outcome in FLEET_REQUEST_OUTCOMES
    # (every request the router ACCEPTS seals in exactly one of these —
    # the fleet-level funnel the drill harness audits). Typed optional
    # fields: replica, retries, status, trace_id, replica_id.
    "fleet_request": {"outcome": str, "path": str},
    # One forward attempt under a routed request (ISSUE 18): the
    # sibling record that turns a retry/hedge into a causal chain —
    # `trace_id` joins it to its `fleet_request` seal (and to the
    # replica-side `serve_request` records carrying the same id),
    # `attempt` is the 0-based index (== retries spent so far), outcome
    # in FLEET_ATTEMPT_OUTCOMES. Typed optional fields: status,
    # backoff_s (the wait that FOLLOWED a failed attempt), path.
    "fleet_attempt": {"trace_id": str, "attempt": int, "replica": str,
                      "outcome": str},
    # Terminal router record; outcome in SERVE_OUTCOMES, stats is
    # FleetRouter.stats().
    "fleet_end": {"outcome": str, "stats": dict},
    # ---- offline batch inference (`pbt map`, ISSUE 14) ----
    # Run manifest: the resolved map configuration (store dir, corpus
    # size, shard/block/row geometry, trunk fingerprint) — the mapping
    # counterpart of run_start.
    "map_start": {"config": dict, "pid": int},
    # One shard lifecycle transition: state in MAP_SHARD_STATES
    # (start/resume/done/halted/failed). Typed optional fields: blocks,
    # next, size (non-negative ints), reason, cursor_source.
    "map_shard": {"shard": int, "state": str},
    # One durably COMMITTED block (emitted only after the cursor
    # advance — the engine's commit point, so counting these across
    # incarnations measures re-work exactly). `digest` is the block
    # payload's sha256. Typed optional fields: retries, quarantined,
    # start, end (non-negative ints), seqs_per_s (non-negative finite).
    "map_block": {"shard": int, "block": int, "digest": str, "n": int},
    # Terminal mapping record; outcome in MAP_OUTCOMES, stats is the
    # run_map result (blocks/seqs/quarantined/retries/rework/...).
    "map_end": {"outcome": str, "stats": dict},
    # ---- neighbor index (`pbt index` + /v1/neighbors, ISSUE 17) ----
    # Build lifecycle: state in INDEX_BUILD_STATES ("start" opens the
    # run with stats={} + extra config/pid; the terminal record carries
    # the builder's stats dict — vectors/blocks/rework/bytes ratio).
    "index_build": {"state": str, "stats": dict},
    # One index-shard lifecycle transition: state in
    # INDEX_SHARD_STATES. Typed optional fields: blocks, next, size,
    # tail_reworked (non-negative ints), cursor_source.
    "index_shard": {"shard": int, "state": str},
    # One served /v1/neighbors lookup (sampled like serve_request —
    # failures always sampled): k/nprobe are the executable's static
    # shape. Typed optional fields: candidates (non-negative int),
    # lookup_s (non-negative finite seconds, the ANN leg),
    # outcome (SERVE_REQUEST_OUTCOMES).
    "neighbor_query": {"k": int, "nprobe": int},
    # ---- blue-green trunk rollout (ISSUE 20) ----
    # One rollout lifecycle transition (controller or replica):
    # state in ROLLOUT_STATES. Typed optional fields: source,
    # fingerprint, reason (strings), windows_green (non-negative int),
    # flip_seconds (non-negative finite seconds).
    "rollout_state": {"state": str},
    # One closed shadow window: verdict in ROLLOUT_VERDICTS. Typed
    # optional fields: parity_max (non-negative finite; absent when a
    # structural mismatch made it unbounded), slo_burn_delta /
    # heads_eval_delta (finite — deltas, negative = the candidate
    # improved), shadow_ok / shadow_failed (non-negative ints).
    "rollout_window": {"window": int, "verdict": str},
    # One mirrored shadow attempt: the `shadow=true` sibling of a live
    # fleet_request under the SAME trace_id — never retried, never
    # user-visible, never cache-writing, and deliberately NOT a
    # fleet_attempt (attempts == retries+1 stays exact). outcome in
    # ROLLOUT_SHADOW_OUTCOMES; `shadow` is the literal-true flag
    # downstream filters key on. Typed optional fields: status (HTTP
    # code, or 0 for a transport failure), parity_max, path.
    "rollout_shadow": {"trace_id": str, "replica": str, "outcome": str,
                      "shadow": bool},
    # One atomic arm swap on a replica: phase in ROLLOUT_FLIP_PHASES;
    # `seconds` is the swap-lock flip (or re-replication rollback)
    # latency. Typed optional fields: fingerprint (the NEW resident
    # trunk), ok (bool).
    "rollout_flip": {"replica": str, "phase": str,
                     "seconds": (int, float)},
    # Fleet trunk-coherence transition from the router's health sweep:
    # state in ROLLOUT_FLEET_STATES; optional `fingerprints` counts the
    # distinct resident fingerprints over routable replicas.
    "rollout_fleet": {"state": str},
}

CKPT_PHASES = ("dispatch", "landed", "save")
OUTCOMES = ("completed", "preempted", "early_stopped", "nan_halt", "error")
SERVE_OUTCOMES = ("drained", "aborted")
SERVE_REJECT_REASONS = ("queue_full", "deadline", "closed", "too_long",
                        "unknown_head")
# Terminal per-request outcomes: ok/cache_hit resolve a result; error is
# a dispatch/finalize failure; expired missed its deadline; evicted lost
# its queue slot to newer work; rejected never got past admission;
# aborted was killed by a hard shutdown.
SERVE_REQUEST_OUTCOMES = ("ok", "cache_hit", "error", "expired",
                          "evicted", "rejected", "aborted")
# Fleet replica health states (serve/fleet.py): up (routable),
# degraded (SLO burn > threshold — deprioritized), dead (health checks
# failing), draining (operator drain: no new work, in-flight finishes),
# admitted (re-admitted after drain or recovery from dead).
FLEET_REPLICA_STATES = ("up", "degraded", "dead", "draining", "admitted")
# Terminal fleet-routed request outcomes: ok (first replica answered),
# cache_hit (the shared result cache short-circuited), retried_ok (a
# retry on another replica answered after a failure), shed (load shed —
# a typed 429/503 passthrough or router-side no-capacity 503), failed
# (a non-retryable error reached the client).
FLEET_REQUEST_OUTCOMES = ("ok", "cache_hit", "retried_ok", "shed",
                          "failed")
# Per-attempt outcomes under one routed request (ISSUE 18): ok (the
# replica answered 200), transport_failed (connection-level failure —
# the retry path's trigger), retryable (the replica answered a
# RETRYABLE status, 503), shed (typed backpressure passthrough,
# 429/504), failed (a non-retryable error answer).
FLEET_ATTEMPT_OUTCOMES = ("ok", "transport_failed", "retryable", "shed",
                          "failed")
# Map shard lifecycle states (mapper/engine.py): start (fresh cursor),
# resume (an existing cursor was picked up — incl. a torn-cursor /
# torn-tail fallback), done (shard exhausted), halted (non-finite
# embeddings — flight dump taken), failed (retry budget exhausted).
MAP_SHARD_STATES = ("start", "resume", "done", "halted", "failed")
# Terminal map-run outcomes: completed (every shard done), preempted
# (SIGTERM/SIGINT or a max-blocks bound — resumable, CLI exits 75),
# halted (a shard hit non-finite output), error (a shard exhausted its
# retry budget).
MAP_OUTCOMES = ("completed", "preempted", "halted", "error")
# Index-build lifecycle states (index/store.py, duplicated here because
# this module must stay import-light): start (run opened), completed,
# preempted (SIGTERM/SIGINT or --max-blocks — resumable, CLI exits
# 75), error.
INDEX_BUILD_STATES = ("start", "completed", "preempted", "error")
# Index shard lifecycle: start (fresh cursor), resume (existing cursor
# picked up — incl. torn-tail / prev-generation fallback), done,
# preempted (stopped mid-shard, resumable).
INDEX_SHARD_STATES = ("start", "resume", "done", "preempted")
# Blue-green rollout lifecycle (rollout/controller.py + serve/server.py,
# ISSUE 20): candidate_loaded/candidate_unloaded are replica-side arm
# events; shadowing → (refused | promoting → promoted → rolled_back) and
# aborted are controller transitions.
ROLLOUT_STATES = ("candidate_loaded", "candidate_unloaded", "shadowing",
                  "refused", "promoting", "promoted", "rolled_back",
                  "aborted")
ROLLOUT_VERDICTS = ("pass", "fail")
ROLLOUT_SHADOW_OUTCOMES = ("ok", "failed")
ROLLOUT_FLIP_PHASES = ("flip", "rollback")
ROLLOUT_FLEET_STATES = ("coherent", "degraded")


def sanitize(value: Any) -> Any:
    """Recursively make `value` strict-JSON-safe: non-finite floats
    become None (a NaN-halt record must stay parseable — NaN/Inf are the
    one payload this log exists to capture and the one thing json.dumps
    emits invalid JSON for), numpy scalars collapse to Python scalars
    via their item()/float semantics, unknown objects become str()."""
    if isinstance(value, bool) or value is None or isinstance(value, str):
        return value
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if isinstance(value, dict):
        return {str(k): sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [sanitize(v) for v in value]
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return sanitize(item())
        except Exception:
            pass
    return str(value)


def make_record(event: str, seq: int, t: float, **fields) -> Dict[str, Any]:
    return {"v": SCHEMA_VERSION, "event": event, "seq": seq,
            "t": round(float(t), 6), **sanitize(fields)}


def build_record(event: str, seq: int, t: float,
                 fields: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """make_record + validate under the never-raises contract: a
    malformed payload (schema violation, or a field colliding with a
    record key — TypeError from make_record) is logged and returns
    None. The ONE construction path for both EventLog.emit and the
    Telemetry facade's flight-only mode."""
    try:
        rec = make_record(event, seq=seq, t=t, **fields)
        validate_record(rec)
        return rec
    except (ValueError, TypeError):
        logger.warning("dropping malformed %r event", event, exc_info=True)
        return None


_SERVE_MODES = ("bucketed", "ragged")
# Quantized serving arms (ISSUE 12, parallel/quant.SERVE_QUANT_MODES;
# duplicated here because this module must stay stdlib-only). "fp32"
# is never emitted (the field is absent on the fp32 arm) but accepted.
_SERVE_QUANT_MODES = ("fp32", "int8", "int8_act")


def _validate_quant_fields(event: str, rec: Dict[str, Any]) -> None:
    """Optional quantized-arm fields shared by serve_batch and
    serve_request (ISSUE 12): `quant` (which executable arm served)
    and, on parity-sampled batches, `quant_parity_max` (worst abs
    deviation vs the fp32 shadow). Typed when present."""
    q = rec.get("quant")
    if q is not None and q not in _SERVE_QUANT_MODES:
        raise ValueError(f"{event}.quant {q!r} not in "
                         f"{_SERVE_QUANT_MODES}")
    pm = rec.get("quant_parity_max")
    if pm is not None and (isinstance(pm, bool)
                           or not isinstance(pm, (int, float))
                           or not math.isfinite(pm) or pm < 0):
        raise ValueError(f"{event}.quant_parity_max must be a "
                         f"non-negative finite number, got {pm!r}")


def _validate_packed_fields(event: str, rec: Dict[str, Any]) -> None:
    """Optional ragged-packing fields shared by serve_batch and
    serve_request (ISSUE 9): typed when present, absent on older
    streams and the bucketed path."""
    seg = rec.get("segments")
    if seg is not None and (not isinstance(seg, int)
                            or isinstance(seg, bool) or seg < 0):
        raise ValueError(f"{event}.segments must be a non-negative int, "
                         f"got {seg!r}")
    spr = rec.get("segments_per_row")
    if spr is not None and (isinstance(spr, bool)
                            or not isinstance(spr, (int, float))
                            or not math.isfinite(spr) or spr < 0):
        raise ValueError(f"{event}.segments_per_row must be a "
                         f"non-negative finite number, got {spr!r}")
    mode = rec.get("mode")
    if mode is not None and mode not in _SERVE_MODES:
        raise ValueError(f"{event}.mode {mode!r} not in {_SERVE_MODES}")
    pf = rec.get("pad_fraction")
    if pf is not None and (isinstance(pf, bool)
                           or not isinstance(pf, (int, float))
                           or not math.isfinite(pf)
                           or not 0.0 <= pf <= 1.0):
        raise ValueError(f"{event}.pad_fraction must be a number in "
                         f"[0, 1], got {pf!r}")


def _validate_trace_fields(event: str, rec: Dict[str, Any]) -> None:
    """Optional fleet-trace join fields (ISSUE 18) shared by
    serve_request, serve_batch, and fleet_request: `trace_id` (the
    fleet-scope id the router minted and the X-PBT-Trace header
    propagated), `parent` (the enclosing fleet request's id), and
    `replica_id` (the --replica-id identity stamped at emit). All
    strings, typed when present — absent on pre-fleet streams and
    standalone servers."""
    for name in ("trace_id", "parent", "replica_id"):
        v = rec.get(name)
        if v is not None and not isinstance(v, str):
            raise ValueError(f"{event}.{name} must be a string, "
                             f"got {v!r}")


def validate_record(rec: Any) -> None:
    """Raise ValueError (with a pinpointing message) unless `rec` is a
    well-formed event record. The writer, tools/validate_events.py, and
    the tier-1 round-trip test all call THIS function — one schema."""
    if not isinstance(rec, dict):
        raise ValueError(f"record is not an object: {type(rec).__name__}")
    if rec.get("v") != SCHEMA_VERSION:
        raise ValueError(f"schema version {rec.get('v')!r} != {SCHEMA_VERSION}")
    event = rec.get("event")
    if event not in EVENT_FIELDS:
        raise ValueError(f"unknown event type {event!r} "
                         f"(have {sorted(EVENT_FIELDS)})")
    seq = rec.get("seq")
    if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
        raise ValueError(f"seq must be a non-negative int, got {seq!r}")
    t = rec.get("t")
    if not isinstance(t, (int, float)) or isinstance(t, bool) \
            or not math.isfinite(t):
        raise ValueError(f"t must be a finite number, got {t!r}")
    for name, typ in EVENT_FIELDS[event].items():
        if name not in rec:
            raise ValueError(f"{event}: missing required field {name!r}")
        if not isinstance(rec[name], typ):
            raise ValueError(
                f"{event}.{name}: expected {typ}, got {type(rec[name]).__name__}")
    if "step" in rec:
        s = rec["step"]
        if not isinstance(s, int) or isinstance(s, bool) or s < 0:
            raise ValueError(f"step must be a non-negative int, got {s!r}")
    if event == "ckpt_stage" and rec["phase"] not in CKPT_PHASES:
        raise ValueError(f"ckpt_stage.phase {rec['phase']!r} not in "
                         f"{CKPT_PHASES}")
    if event == "run_end" and rec["outcome"] not in OUTCOMES:
        raise ValueError(f"run_end.outcome {rec['outcome']!r} not in "
                         f"{OUTCOMES}")
    if event == "serve_end" and rec["outcome"] not in SERVE_OUTCOMES:
        raise ValueError(f"serve_end.outcome {rec['outcome']!r} not in "
                         f"{SERVE_OUTCOMES}")
    if event == "serve_reject":
        if rec["reason"] not in SERVE_REJECT_REASONS:
            raise ValueError(f"serve_reject.reason {rec['reason']!r} not in "
                             f"{SERVE_REJECT_REASONS}")
        # queue_depth is optional (older streams predate it) but typed.
        qd = rec.get("queue_depth")
        if qd is not None and (not isinstance(qd, int)
                               or isinstance(qd, bool) or qd < 0):
            raise ValueError(f"serve_reject.queue_depth must be a "
                             f"non-negative int, got {qd!r}")
    if event == "serve_batch":
        for field in ("bucket_len", "rows"):
            v = rec[field]
            if isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"serve_batch.{field} must be a non-negative int, "
                    f"got {v!r}")
        _validate_packed_fields(event, rec)
        _validate_quant_fields(event, rec)
        _validate_trace_fields(event, rec)
    if event == "serve_request":
        _validate_packed_fields(event, rec)
        _validate_quant_fields(event, rec)
        _validate_trace_fields(event, rec)
        if rec["outcome"] not in SERVE_REQUEST_OUTCOMES:
            raise ValueError(f"serve_request.outcome {rec['outcome']!r} "
                             f"not in {SERVE_REQUEST_OUTCOMES}")
        for name, v in rec["stages"].items():
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or not math.isfinite(v) or v < 0:
                raise ValueError(
                    f"serve_request.stages[{name!r}] must be a "
                    f"non-negative finite number, got {v!r}")
        # head_id is optional (only predict_task requests carry one —
        # the per-tenant attribution field of diagnose --serve) but
        # typed when present.
        hid = rec.get("head_id")
        if hid is not None and not isinstance(hid, str):
            raise ValueError(f"serve_request.head_id must be a string, "
                             f"got {hid!r}")
    if event == "head_eval":
        for name, v in rec["metrics"].items():
            if isinstance(v, bool) or (
                    not isinstance(v, (int, float, str))
                    and v is not None):
                raise ValueError(
                    f"head_eval.metrics[{name!r}] must be a number, "
                    f"string, or null, got {type(v).__name__}")
    if event == "slo_breach":
        br = rec["burn_rate"]
        if isinstance(br, bool) or not math.isfinite(br) or br < 0:
            raise ValueError(f"slo_breach.burn_rate must be a "
                             f"non-negative finite number, got {br!r}")
    if event == "reshard":
        for name, v in rec["wire_bytes"].items():
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"reshard.wire_bytes[{name!r}] must be a "
                    f"non-negative int, got {v!r}")
        for k in rec["target_mesh"]:
            if not isinstance(k, str):
                raise ValueError(
                    f"reshard.target_mesh keys must be axis names, "
                    f"got {k!r}")
    if event == "fleet_replica" and rec["state"] not in FLEET_REPLICA_STATES:
        raise ValueError(f"fleet_replica.state {rec['state']!r} not in "
                         f"{FLEET_REPLICA_STATES}")
    if event == "fleet_request":
        if rec["outcome"] not in FLEET_REQUEST_OUTCOMES:
            raise ValueError(f"fleet_request.outcome {rec['outcome']!r} "
                             f"not in {FLEET_REQUEST_OUTCOMES}")
        retries = rec.get("retries")
        if retries is not None and (not isinstance(retries, int)
                                    or isinstance(retries, bool)
                                    or retries < 0):
            raise ValueError(f"fleet_request.retries must be a "
                             f"non-negative int, got {retries!r}")
        status = rec.get("status")
        if status is not None and (not isinstance(status, int)
                                   or isinstance(status, bool)
                                   or not 100 <= status <= 599):
            raise ValueError(f"fleet_request.status must be an HTTP "
                             f"status code, got {status!r}")
        rep = rec.get("replica")
        if rep is not None and not isinstance(rep, str):
            raise ValueError(f"fleet_request.replica must be a string, "
                             f"got {rep!r}")
        _validate_trace_fields(event, rec)
    if event == "fleet_attempt":
        if rec["outcome"] not in FLEET_ATTEMPT_OUTCOMES:
            raise ValueError(f"fleet_attempt.outcome {rec['outcome']!r} "
                             f"not in {FLEET_ATTEMPT_OUTCOMES}")
        att = rec["attempt"]
        if isinstance(att, bool) or att < 0:
            raise ValueError(f"fleet_attempt.attempt must be a "
                             f"non-negative int, got {att!r}")
        status = rec.get("status")
        if status is not None and (not isinstance(status, int)
                                   or isinstance(status, bool)
                                   or not 100 <= status <= 599):
            raise ValueError(f"fleet_attempt.status must be an HTTP "
                             f"status code, got {status!r}")
        bo = rec.get("backoff_s")
        if bo is not None and (isinstance(bo, bool)
                               or not isinstance(bo, (int, float))
                               or not math.isfinite(bo) or bo < 0):
            raise ValueError(f"fleet_attempt.backoff_s must be a "
                             f"non-negative finite number, got {bo!r}")
        path = rec.get("path")
        if path is not None and not isinstance(path, str):
            raise ValueError(f"fleet_attempt.path must be a string, "
                             f"got {path!r}")
    if event == "fleet_end" and rec["outcome"] not in SERVE_OUTCOMES:
        raise ValueError(f"fleet_end.outcome {rec['outcome']!r} not in "
                         f"{SERVE_OUTCOMES}")
    if event in ("map_shard", "map_block"):
        for name in ("shard", "block", "n", "blocks", "next", "size",
                     "start", "end", "retries", "quarantined"):
            v = rec.get(name)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise ValueError(f"{event}.{name} must be a "
                                 f"non-negative int, got {v!r}")
    if event == "map_shard" and rec["state"] not in MAP_SHARD_STATES:
        raise ValueError(f"map_shard.state {rec['state']!r} not in "
                         f"{MAP_SHARD_STATES}")
    if event == "map_block":
        dg = rec["digest"]
        if len(dg) != 64 or any(c not in "0123456789abcdef" for c in dg):
            raise ValueError(f"map_block.digest must be a lowercase "
                             f"sha256 hex digest, got {dg!r}")
        sps = rec.get("seqs_per_s")
        if sps is not None and (isinstance(sps, bool)
                                or not isinstance(sps, (int, float))
                                or not math.isfinite(sps) or sps < 0):
            raise ValueError(f"map_block.seqs_per_s must be a "
                             f"non-negative finite number, got {sps!r}")
    if event == "map_end" and rec["outcome"] not in MAP_OUTCOMES:
        raise ValueError(f"map_end.outcome {rec['outcome']!r} not in "
                         f"{MAP_OUTCOMES}")
    if event == "index_build" and rec["state"] not in INDEX_BUILD_STATES:
        raise ValueError(f"index_build.state {rec['state']!r} not in "
                         f"{INDEX_BUILD_STATES}")
    if event == "index_shard":
        if rec["state"] not in INDEX_SHARD_STATES:
            raise ValueError(f"index_shard.state {rec['state']!r} not "
                             f"in {INDEX_SHARD_STATES}")
        for name in ("shard", "blocks", "next", "size", "tail_reworked"):
            v = rec.get(name)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise ValueError(f"index_shard.{name} must be a "
                                 f"non-negative int, got {v!r}")
    if event == "neighbor_query":
        for name in ("k", "nprobe"):
            v = rec[name]
            if isinstance(v, bool) or v < 1:
                raise ValueError(f"neighbor_query.{name} must be a "
                                 f"positive int, got {v!r}")
        cand = rec.get("candidates")
        if cand is not None and (not isinstance(cand, int)
                                 or isinstance(cand, bool) or cand < 0):
            raise ValueError(f"neighbor_query.candidates must be a "
                             f"non-negative int, got {cand!r}")
        ls = rec.get("lookup_s")
        if ls is not None and (isinstance(ls, bool)
                               or not isinstance(ls, (int, float))
                               or not math.isfinite(ls) or ls < 0):
            raise ValueError(f"neighbor_query.lookup_s must be a "
                             f"non-negative finite number, got {ls!r}")
        oc = rec.get("outcome")
        if oc is not None and oc not in SERVE_REQUEST_OUTCOMES:
            raise ValueError(f"neighbor_query.outcome {oc!r} not in "
                             f"{SERVE_REQUEST_OUTCOMES}")
    if event == "rollout_state":
        if rec["state"] not in ROLLOUT_STATES:
            raise ValueError(f"rollout_state.state {rec['state']!r} not "
                             f"in {ROLLOUT_STATES}")
        for name in ("source", "fingerprint", "reason"):
            v = rec.get(name)
            if v is not None and not isinstance(v, str):
                raise ValueError(f"rollout_state.{name} must be a "
                                 f"string, got {v!r}")
        wg = rec.get("windows_green")
        if wg is not None and (not isinstance(wg, int)
                               or isinstance(wg, bool) or wg < 0):
            raise ValueError(f"rollout_state.windows_green must be a "
                             f"non-negative int, got {wg!r}")
        fs = rec.get("flip_seconds")
        if fs is not None and (isinstance(fs, bool)
                               or not isinstance(fs, (int, float))
                               or not math.isfinite(fs) or fs < 0):
            raise ValueError(f"rollout_state.flip_seconds must be a "
                             f"non-negative finite number, got {fs!r}")
    if event == "rollout_window":
        if rec["verdict"] not in ROLLOUT_VERDICTS:
            raise ValueError(f"rollout_window.verdict "
                             f"{rec['verdict']!r} not in "
                             f"{ROLLOUT_VERDICTS}")
        w = rec["window"]
        if isinstance(w, bool) or w < 0:
            raise ValueError(f"rollout_window.window must be a "
                             f"non-negative int, got {w!r}")
        pm = rec.get("parity_max")
        if pm is not None and (isinstance(pm, bool)
                               or not isinstance(pm, (int, float))
                               or not math.isfinite(pm) or pm < 0):
            raise ValueError(f"rollout_window.parity_max must be a "
                             f"non-negative finite number, got {pm!r}")
        for name in ("slo_burn_delta", "heads_eval_delta"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v)):
                raise ValueError(f"rollout_window.{name} must be a "
                                 f"finite number, got {v!r}")
        for name in ("shadow_ok", "shadow_failed"):
            v = rec.get(name)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise ValueError(f"rollout_window.{name} must be a "
                                 f"non-negative int, got {v!r}")
    if event == "rollout_shadow":
        if rec["outcome"] not in ROLLOUT_SHADOW_OUTCOMES:
            raise ValueError(f"rollout_shadow.outcome "
                             f"{rec['outcome']!r} not in "
                             f"{ROLLOUT_SHADOW_OUTCOMES}")
        if rec["shadow"] is not True:
            # The invisibility audit filters on shadow==true; a record
            # claiming to be a shadow while flagging false would let
            # shadow traffic masquerade as live (or vice versa).
            raise ValueError(f"rollout_shadow.shadow must be literally "
                             f"true, got {rec['shadow']!r}")
        status = rec.get("status")
        if status is not None and (not isinstance(status, int)
                                   or isinstance(status, bool)
                                   or not (status == 0
                                           or 100 <= status <= 599)):
            raise ValueError(f"rollout_shadow.status must be an HTTP "
                             f"status code (or 0 for a transport "
                             f"failure), got {status!r}")
        pm = rec.get("parity_max")
        if pm is not None and (isinstance(pm, bool)
                               or not isinstance(pm, (int, float))
                               or not math.isfinite(pm) or pm < 0):
            raise ValueError(f"rollout_shadow.parity_max must be a "
                             f"non-negative finite number, got {pm!r}")
        path = rec.get("path")
        if path is not None and not isinstance(path, str):
            raise ValueError(f"rollout_shadow.path must be a string, "
                             f"got {path!r}")
    if event == "rollout_flip":
        if rec["phase"] not in ROLLOUT_FLIP_PHASES:
            raise ValueError(f"rollout_flip.phase {rec['phase']!r} not "
                             f"in {ROLLOUT_FLIP_PHASES}")
        s = rec["seconds"]
        if isinstance(s, bool) or not math.isfinite(s) or s < 0:
            raise ValueError(f"rollout_flip.seconds must be a "
                             f"non-negative finite number, got {s!r}")
        fp = rec.get("fingerprint")
        if fp is not None and not isinstance(fp, str):
            raise ValueError(f"rollout_flip.fingerprint must be a "
                             f"string, got {fp!r}")
        ok = rec.get("ok")
        if ok is not None and not isinstance(ok, bool):
            raise ValueError(f"rollout_flip.ok must be a bool, "
                             f"got {ok!r}")
    if event == "rollout_fleet":
        if rec["state"] not in ROLLOUT_FLEET_STATES:
            raise ValueError(f"rollout_fleet.state {rec['state']!r} not "
                             f"in {ROLLOUT_FLEET_STATES}")
        n = rec.get("fingerprints")
        if n is not None and (not isinstance(n, int)
                              or isinstance(n, bool) or n < 0):
            raise ValueError(f"rollout_fleet.fingerprints must be a "
                             f"non-negative int, got {n!r}")
    if event == "note" and rec.get("kind") == "rollout_capture":
        # The rollout drill capture (tools/rollout_drill.py): worst
        # shadow parity through the good candidate + the atomic-flip
        # latency are trajectory-sentinel inputs (both lower-is-
        # better), so a writer bug must fail validation, not poison
        # the series.
        for name in ("rollout_shadow_parity_max", "rollout_flip_seconds"):
            v = rec.get(name)
            if v is None:
                raise ValueError(
                    f"note(kind=rollout_capture): missing required "
                    f"field {name!r}")
            if (isinstance(v, bool) or not isinstance(v, (int, float))
                    or not math.isfinite(v) or v < 0):
                raise ValueError(
                    f"note(kind=rollout_capture).{name} must be a "
                    f"non-negative finite number, got {v!r}")
    if event == "note" and rec.get("kind") == "map_capture":
        # The map-throughput capture (tools/map_drill.py --bench-events):
        # its rate field is a trajectory-sentinel input, so a writer bug
        # must fail validation, not poison the series.
        v = rec.get("map_seqs_per_s")
        if v is None:
            raise ValueError(
                "note(kind=map_capture): missing required field "
                "'map_seqs_per_s'")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0):
            raise ValueError(
                f"note(kind=map_capture).map_seqs_per_s must be a "
                f"positive finite number, got {v!r}")
        # Pipelined-mapper overlap evidence (ISSUE 19): the share of
        # host fetch+commit seconds spent with a later block's device
        # compute enqueued — a ratio, so [0, 1] by construction.
        r = rec.get("map_overlap_ratio")
        if r is not None and (isinstance(r, bool)
                              or not isinstance(r, (int, float))
                              or not math.isfinite(r)
                              or not 0.0 <= r <= 1.0):
            raise ValueError(
                f"note(kind=map_capture).map_overlap_ratio must be a "
                f"number in [0, 1], got {r!r}")
    if event == "note" and rec.get("kind") == "check_capture":
        # The static-analyzer capture (`pbt check --events-jsonl`,
        # ISSUE 15): check_findings_total (new + baselined findings) is
        # the trajectory sentinel's suppression-creep series, so a
        # writer bug must fail validation, not poison the series.
        for name in ("check_findings_total", "check_baselined_total"):
            v = rec.get(name)
            if name == "check_findings_total" and v is None:
                raise ValueError(
                    "note(kind=check_capture): missing required field "
                    "'check_findings_total'")
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise ValueError(
                    f"note(kind=check_capture).{name} must be a "
                    f"non-negative int, got {v!r}")
    if event == "note" and rec.get("kind") == "restore_fallback":
        # The checkpointer's torn-final-checkpoint fallback report
        # (train/checkpoint.py): bad_step (the skipped torn step) is
        # required; landed_step (the step actually restored, ISSUE 14
        # satellite) is typed when present (older streams predate it).
        bs = rec.get("bad_step")
        if not isinstance(bs, int) or isinstance(bs, bool) or bs < 0:
            raise ValueError(
                f"note(kind=restore_fallback).bad_step must be a "
                f"non-negative int, got {bs!r}")
        ls = rec.get("landed_step")
        if ls is not None and (not isinstance(ls, int)
                               or isinstance(ls, bool) or ls < 0):
            raise ValueError(
                f"note(kind=restore_fallback).landed_step must be a "
                f"non-negative int, got {ls!r}")
    if event == "note" and rec.get("kind") == "comm_quant":
        # The quantized-collectives capture (bench.py --comm, ISSUE
        # 12): its ratio fields are the trajectory-sentinel inputs, so
        # a writer bug must fail validation, not poison the series.
        for name in ("int8_grad_wire_ratio", "bf16_grad_wire_ratio"):
            v = rec.get(name)
            if name == "int8_grad_wire_ratio" and v is None:
                raise ValueError(
                    "note(kind=comm_quant): missing required field "
                    "'int8_grad_wire_ratio'")
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v <= 0):
                raise ValueError(
                    f"note(kind=comm_quant).{name} must be a positive "
                    f"finite number, got {v!r}")
    if event == "note" and rec.get("kind") == "pack_attn_capture":
        # The ragged-attention A/B capture (bench.py --pack, ISSUE 13):
        # its speedup/MFU fields feed trajectory-sentinel series, so a
        # writer bug must fail validation, not poison the series.
        v = rec.get("attn_speedup_x")
        if v is None:
            raise ValueError(
                "note(kind=pack_attn_capture): missing required field "
                "'attn_speedup_x'")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0):
            raise ValueError(
                f"note(kind=pack_attn_capture).attn_speedup_x must be "
                f"a positive finite number, got {v!r}")
        for name in ("mfu_effective", "mfu_raw", "parity_max_abs_diff"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v < 0):
                raise ValueError(
                    f"note(kind=pack_attn_capture).{name} must be a "
                    f"non-negative finite number, got {v!r}")
    if event == "note" and rec.get("kind") == "onepass_capture":
        # The one-pass trunk A/B capture (bench.py --pack, ISSUE 16):
        # single fused block-pass kernel vs the two-kernel composition.
        # Its speedup/MFU fields feed trajectory-sentinel series, so a
        # writer bug must fail validation, not poison the series.
        v = rec.get("onepass_speedup_x")
        if v is None:
            raise ValueError(
                "note(kind=onepass_capture): missing required field "
                "'onepass_speedup_x'")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0):
            raise ValueError(
                f"note(kind=onepass_capture).onepass_speedup_x must be "
                f"a positive finite number, got {v!r}")
        for name in ("mfu_effective", "mfu_raw", "parity_max_abs_diff"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v < 0):
                raise ValueError(
                    f"note(kind=onepass_capture).{name} must be a "
                    f"non-negative finite number, got {v!r}")
    if event == "note" and rec.get("kind") == "fleet_trace_capture":
        # The fleet-propagation overhead A/B (bench.py --serve fleet
        # arm, ISSUE 18): routed-throughput delta with trace
        # propagation on vs off. The pct is a trajectory-sentinel
        # input (lower-is-better), so a writer bug must fail
        # validation, not poison the series. It is a DIFFERENCE, so
        # negative values (measurement noise) are legal — finiteness
        # is the bound.
        v = rec.get("fleet_trace_overhead_pct")
        if v is None:
            raise ValueError(
                "note(kind=fleet_trace_capture): missing required "
                "field 'fleet_trace_overhead_pct'")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v)):
            raise ValueError(
                f"note(kind=fleet_trace_capture).fleet_trace_overhead_"
                f"pct must be a finite number, got {v!r}")
        for name in ("fleet_rps_on", "fleet_rps_off"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v <= 0):
                raise ValueError(
                    f"note(kind=fleet_trace_capture).{name} must be a "
                    f"positive finite number, got {v!r}")
        # ISSUE 19 satellite: the pct is the MEDIAN over this many A/B
        # rounds (the PR 18 single-round number sign-flipped under
        # load); typed when present so the sentinel can trust it.
        n = rec.get("rounds")
        if n is not None and (not isinstance(n, int)
                              or isinstance(n, bool) or n < 1):
            raise ValueError(
                f"note(kind=fleet_trace_capture).rounds must be a "
                f"positive int, got {n!r}")
    if event == "note" and rec.get("kind") == "neighbors_capture":
        # The ANN serving capture (bench.py --neighbors, ISSUE 17):
        # its QPS and recall fields feed trajectory-sentinel series
        # (recall is HIGHER-is-better), so a writer bug must fail
        # validation, not poison the series.
        for name in ("neighbors_qps", "neighbors_recall_at_10"):
            v = rec.get(name)
            if v is None:
                raise ValueError(
                    f"note(kind=neighbors_capture): missing required "
                    f"field {name!r}")
        v = rec.get("neighbors_qps")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0):
            raise ValueError(
                f"note(kind=neighbors_capture).neighbors_qps must be "
                f"a positive finite number, got {v!r}")
        r = rec.get("neighbors_recall_at_10")
        if (isinstance(r, bool) or not isinstance(r, (int, float))
                or not math.isfinite(r) or not 0.0 <= r <= 1.0):
            raise ValueError(
                f"note(kind=neighbors_capture).neighbors_recall_at_10 "
                f"must be a number in [0, 1], got {r!r}")
        for name in ("embed_qps", "neighbors_qps_ratio",
                     "index_bytes_ratio"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v <= 0):
                raise ValueError(
                    f"note(kind=neighbors_capture).{name} must be a "
                    f"positive finite number, got {v!r}")
    if event == "note" and rec.get("kind") == "serve_pipeline_capture":
        # The pipelined-dispatch A/B capture (bench.py --serve pipeline
        # phase, ISSUE 19): depth-2 vs depth-1 served throughput, gated
        # on async-vs-sync output bit-parity and exactly-once sealing
        # under drain with work in flight. The speedup is a trajectory-
        # sentinel input, so a writer bug must fail validation, not
        # poison the series.
        v = rec.get("serve_pipeline_speedup_x")
        if v is None:
            raise ValueError(
                "note(kind=serve_pipeline_capture): missing required "
                "field 'serve_pipeline_speedup_x'")
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or not math.isfinite(v) or v <= 0):
            raise ValueError(
                f"note(kind=serve_pipeline_capture)."
                f"serve_pipeline_speedup_x must be a positive finite "
                f"number, got {v!r}")
        for name in ("pipeline_rps", "serial_rps"):
            v = rec.get(name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v <= 0):
                raise ValueError(
                    f"note(kind=serve_pipeline_capture).{name} must be "
                    f"a positive finite number, got {v!r}")
        r = rec.get("serve_overlap_ratio")
        if r is not None and (isinstance(r, bool)
                              or not isinstance(r, (int, float))
                              or not math.isfinite(r)
                              or not 0.0 <= r <= 1.0):
            raise ValueError(
                f"note(kind=serve_pipeline_capture).serve_overlap_"
                f"ratio must be a number in [0, 1], got {r!r}")
        im = rec.get("inflight_max")
        if im is not None and (not isinstance(im, int)
                               or isinstance(im, bool) or im < 0):
            raise ValueError(
                f"note(kind=serve_pipeline_capture).inflight_max must "
                f"be a non-negative int, got {im!r}")


def make_example(event: str) -> Dict[str, Any]:
    """A minimal valid record of `event` — the self-test/round-trip
    fixture, kept next to the schema so adding an event type without a
    fixture fails the validator self-test immediately."""
    payloads = {
        "run_start": {"config": {"train": {"max_steps": 1}},
                      "jax_version": "0.0.0", "pid": 1},
        "step": {"step": 1, "metrics": {"loss": 1.0}},
        "ckpt_stage": {"step": 1, "phase": "dispatch"},
        "eval": {"step": 1, "metrics": {"eval_loss": 1.0}},
        "requeue": {"step": 1, "reason": "signal_15"},
        "nan_halt": {"step": 1, "metrics": {"loss": None}},
        "run_end": {"outcome": "completed", "perf": {}},
        "note": {"source": "self_test"},
        "serve_start": {"config": {"max_batch": 8}, "pid": 1},
        "serve_batch": {"kind": "embed", "bucket_len": 128, "rows": 4},
        "serve_reject": {"reason": "queue_full", "queue_depth": 4},
        "serve_end": {"outcome": "drained", "stats": {"requests": 0}},
        "serve_request": {"kind": "embed", "outcome": "ok",
                          "request_id": "r000001",
                          "stages": {"queue": 0.001, "execute": 0.004}},
        "slo_breach": {"objective": "latency_e2e", "burn_rate": 2.5},
        "head_registered": {"head_id": "a1b2c3d4e5f60708",
                            "kind": "token_classification"},
        "head_eval": {"head_id": "a1b2c3d4e5f60708",
                      "metrics": {"per_residue_accuracy": 0.9,
                                  "score": 0.9}},
        "reshard": {"step": 1, "target_mesh": {"data": 4, "fsdp": 2},
                    "wire_bytes": {"all-gather": 1024, "total": 1024}},
        "fleet_start": {"config": {"replicas": 3}, "pid": 1},
        "fleet_replica": {"replica": "r0", "state": "up"},
        "fleet_request": {"outcome": "ok", "path": "/v1/embed",
                          "replica": "r0", "retries": 0, "status": 200,
                          "trace_id": "f1-1"},
        "fleet_attempt": {"trace_id": "f1-1", "attempt": 0,
                          "replica": "r0", "outcome": "ok",
                          "status": 200, "path": "/v1/embed"},
        "fleet_end": {"outcome": "drained", "stats": {"accepted": 0}},
        "map_start": {"config": {"num_shards": 2}, "pid": 1},
        "map_shard": {"shard": 0, "state": "start", "next": 0,
                      "size": 16},
        "map_block": {"shard": 0, "block": 0, "digest": "0" * 64,
                      "n": 8, "seqs_per_s": 12.5},
        "map_end": {"outcome": "completed", "stats": {"blocks": 1}},
        "index_build": {"state": "start", "stats": {}, "pid": 1},
        "index_shard": {"shard": 0, "state": "start", "next": 0,
                        "size": 16},
        "neighbor_query": {"k": 10, "nprobe": 8, "candidates": 64,
                           "lookup_s": 0.001, "outcome": "ok"},
        "rollout_state": {"state": "shadowing", "source": "good",
                          "fingerprint": "f" * 64, "windows_green": 0},
        "rollout_window": {"window": 0, "verdict": "pass",
                           "parity_max": 0.0001, "slo_burn_delta": 0.0,
                           "heads_eval_delta": 0.0, "shadow_ok": 8,
                           "shadow_failed": 0},
        "rollout_shadow": {"trace_id": "f1-1", "replica": "r0",
                           "outcome": "ok", "shadow": True,
                           "status": 200, "parity_max": 0.0,
                           "path": "/v1/embed"},
        "rollout_flip": {"replica": "r0", "phase": "flip",
                         "seconds": 0.01, "fingerprint": "f" * 64,
                         "ok": True},
        "rollout_fleet": {"state": "coherent", "fingerprints": 1},
    }
    return make_record(event, seq=0, t=0.0, **payloads[event])


class EventLog:
    """Append-only JSONL event writer.

    - line-buffered file (crash loses at most the in-flight line);
    - thread-safe (the checkpoint stager thread emits from off-main);
    - `seq` monotonic per process, `t` clamped non-decreasing;
    - NEVER raises from emit(): telemetry must not be able to kill a
      training run — a failing disk logs one warning and disables the
      writer, the run continues.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "a", buffering=1)
        self._lock = threading.Lock()
        self._seq = 0          # guarded-by: _lock
        self._last_t = 0.0     # guarded-by: _lock
        self._dead = False     # guarded-by: _lock

    def emit(self, event: str, **fields) -> Optional[Dict[str, Any]]:
        """Validate + append one record; returns it (also handed to the
        flight recorder by the Telemetry facade), or None on failure."""
        with self._lock:
            t = max(time.time(), self._last_t)
            self._last_t = t
            rec = build_record(event, self._seq, t, fields)
            if rec is None:
                return None
            self._seq += 1
            if not self._dead:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                except (OSError, ValueError):
                    # ValueError: write on a closed file (interpreter
                    # teardown / double-close races).
                    self._dead = True
                    logger.warning("event log %s failed; telemetry "
                                   "writes disabled", self.path,
                                   exc_info=True)
            return rec

    def close(self) -> None:
        with self._lock:
            self._dead = True
            try:
                self._fh.close()
            except OSError:
                pass


def read_events(path: str, strict: bool = False) -> List[Dict[str, Any]]:
    """Load an events JSONL. A torn final line (crash mid-write) is
    skipped silently; any OTHER malformed line raises only under
    `strict` (the validator tool) and is skipped with a warning
    otherwise (diagnose must work on imperfect artifacts)."""
    with open(path) as f:
        lines = [(i, ln) for i, ln in enumerate(f, start=1) if ln.strip()]
    records: List[Dict[str, Any]] = []
    for lineno, line in lines:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            # Only UNPARSEABLE JSON on the FINAL line is mid-write
            # tearing; a parseable-but-schema-invalid last record is a
            # writer bug and must not be silently absorbed by strict.
            if lineno == lines[-1][0]:
                break  # torn tail from a crash mid-write
            if strict:
                raise ValueError(f"{path}:{lineno}: {e}") from None
            logger.warning("%s:%d: skipping unparseable line (%s)",
                           path, lineno, e)
            continue
        try:
            validate_record(rec)
        except ValueError as e:
            if strict:
                raise ValueError(f"{path}:{lineno}: {e}") from None
            logger.warning("%s:%d: skipping bad record (%s)",
                           path, lineno, e)
            continue
        records.append(rec)
    return records
