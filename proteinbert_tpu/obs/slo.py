"""Service-level objectives over the serving path (ISSUE 6 tentpole).

Declarative objectives → windowed burn rates → breach actions:

- **`SLObjective`** — one declarative objective, parseable from a
  config dict or a `key=value,...` CLI string (`pbt serve --slo`).
  Two kinds:
  - `latency`: at least `target` of served requests must finish the
    given `stage` (default the whole request, `e2e`) within
    `threshold_s`;
  - `error_rate`: at most `1 - target` of requests may end in a
    server-caused failure (`error` / `expired` outcomes).
- **`SLOEvaluator`** — feeds on per-request completions (outcome,
  end-to-end seconds, optional per-stage attribution from a
  `RequestTrace`) and maintains, per objective, a sliding
  `window_s`-second window with its **burn rate**: the fraction of the
  error budget (`1 - target`) being consumed —
  `bad_fraction / (1 - target)`. Burn 1.0 = exactly consuming budget;
  2.0 = burning at twice the sustainable rate. Surfaced on the metrics
  registry (`slo_burn_rate{objective=}` gauges → `/metrics`),
  `Server.stats()["slo"]`, and `pbt diagnose --serve`.
- **exemplar-linked histograms** — each latency objective keeps a
  bucketed histogram of observed values where every bucket remembers
  its most recent exemplar (request id + value + time): a burn-rate
  page links straight to a traced request to blame. Violating requests
  additionally accumulate a per-stage **attribution** (queue vs
  compute vs padding waste — `pad_wasted` is `execute × pad_fraction`,
  fed by the server), so "p99 breached" comes with "…and the time went
  HERE".
- **`ProfileTrigger`** — an `on_breach` action that captures an
  on-demand device profile via `jax.profiler.start_trace` (stopped by
  a timer thread after `duration_s`), with a cooldown so a sustained
  breach cannot fill the disk. jax is looked up through `sys.modules`
  (never imported here): on an artifact-only machine the trigger
  degrades to a no-op, and the obs package stays jax-free.

Everything takes an injected clock, so burn-rate math is exact under a
fake clock (tests/test_slo.py). Never raises into the serving path.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import logging
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

SLO_KINDS = ("latency", "error_rate")

# Outcomes a LATENCY objective judges: the request was actually served
# (or should have been — errors/expiries are latency violations too).
# Admission-control outcomes (evicted/rejected/aborted) are excluded:
# they are load shedding, tracked by error_rate objectives if desired.
_LATENCY_OUTCOMES = ("ok", "cache_hit", "error", "expired")

DEFAULT_BAD_OUTCOMES = ("error", "expired")

# Default exemplar-histogram bucket upper bounds (seconds, log-spaced).
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Durations a stage-scoped latency objective may target: "e2e" plus the
# request-trace stage names (serve/trace.STAGES — tests assert the two
# stay in sync) and the synthetic padding-waste attribution the server
# derives. A typo'd stage must fail at parse time, not silently judge
# the wrong duration.
VALID_STAGES = ("e2e", "submit", "queue", "batch_form", "dispatch",
                "execute", "lookup", "finalize", "pad_wasted")


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declarative objective (see module doc)."""

    name: str
    kind: str                              # in SLO_KINDS
    target: float = 0.99                   # required good fraction
    window_s: float = 300.0
    threshold_s: Optional[float] = None    # latency only
    stage: str = "e2e"                     # latency only: which duration
    bad_outcomes: Tuple[str, ...] = DEFAULT_BAD_OUTCOMES

    def __post_init__(self):
        if self.kind not in SLO_KINDS:
            raise ValueError(f"slo kind must be one of {SLO_KINDS}, "
                             f"got {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got "
                             f"{self.target!r} — 1.0 leaves no error "
                             "budget to burn")
        if self.window_s <= 0:
            raise ValueError(f"slo window_s must be > 0, got "
                             f"{self.window_s!r}")
        if self.kind == "latency":
            if self.threshold_s is None or self.threshold_s <= 0:
                raise ValueError(
                    f"latency slo {self.name!r} needs threshold_s > 0 "
                    f"(or threshold_ms), got {self.threshold_s!r}")
            if self.stage not in VALID_STAGES:
                raise ValueError(
                    f"latency slo {self.name!r}: unknown stage "
                    f"{self.stage!r} (valid: {VALID_STAGES})")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def parse_slo(spec) -> SLObjective:
    """Build an objective from a dict (config) or a `key=value,...`
    string (CLI), e.g.:

        kind=latency,threshold_ms=250,target=0.99,window_s=300
        name=go_errors,kind=error_rate,target=0.999
        kind=latency,stage=execute,threshold_ms=50

    Accepted keys: name, kind, target (`0.99` or `99%`), window_s,
    threshold_s / threshold_ms, stage, bad_outcomes (`a|b`)."""
    if isinstance(spec, SLObjective):
        return spec
    if isinstance(spec, str):
        fields: Dict[str, str] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"slo spec item {part!r} is not "
                                 f"key=value (spec: {spec!r})")
            k, _, v = part.partition("=")
            fields[k.strip()] = v.strip()
        spec = fields
    if not isinstance(spec, dict):
        raise ValueError(f"slo spec must be a dict or key=value string, "
                         f"got {type(spec).__name__}")
    spec = dict(spec)
    kind = spec.pop("kind", None)
    if kind is None:
        raise ValueError("slo spec needs kind=latency or kind=error_rate")
    target = spec.pop("target", 0.99)
    if isinstance(target, str):
        target = (float(target[:-1]) / 100.0 if target.endswith("%")
                  else float(target))
    threshold_s = spec.pop("threshold_s", None)
    if "threshold_ms" in spec:
        if threshold_s is not None:
            raise ValueError("give threshold_s OR threshold_ms, not both")
        threshold_s = float(spec.pop("threshold_ms")) / 1000.0
    if threshold_s is not None:
        threshold_s = float(threshold_s)
    stage = spec.pop("stage", "e2e")
    window_s = float(spec.pop("window_s", 300.0))
    bad = spec.pop("bad_outcomes", None)
    if isinstance(bad, str):
        bad = tuple(b for b in bad.split("|") if b)
    name = spec.pop("name", None)
    if name is None:
        name = (f"{kind}_{stage}" if kind == "latency" else kind)
    if spec:
        raise ValueError(f"unknown slo spec key(s): {sorted(spec)}")
    kwargs: Dict[str, Any] = dict(name=str(name), kind=str(kind),
                                  target=float(target),
                                  window_s=window_s,
                                  threshold_s=threshold_s, stage=stage)
    if bad is not None:
        kwargs["bad_outcomes"] = tuple(bad)
    return SLObjective(**kwargs)


def parse_slos(specs: Optional[Sequence]) -> List[SLObjective]:
    objectives = [parse_slo(s) for s in (specs or [])]
    names = [o.name for o in objectives]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate slo objective name(s): "
                         f"{sorted(dupes)} — give name=... to "
                         "disambiguate")
    return objectives


class ExemplarHistogram:
    """Fixed-bucket histogram where each bucket remembers its most
    recent exemplar — the (request_id, value, t) to pull up when a
    dashboard asks "show me one of THOSE requests"."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("exemplar histogram needs >= 1 bucket bound")
        # One extra overflow bucket for values past the last bound.
        self.counts = [0] * (len(self.bounds) + 1)
        self.exemplars: List[Optional[Dict[str, Any]]] = (
            [None] * (len(self.bounds) + 1))

    def observe(self, value: float, exemplar_id: Optional[str] = None,
                t: Optional[float] = None) -> None:
        i = bisect.bisect_left(self.bounds, value)
        self.counts[i] += 1
        if exemplar_id is not None:
            self.exemplars[i] = {"request_id": exemplar_id,
                                 "value": round(float(value), 9), "t": t}

    def snapshot(self) -> List[Dict[str, Any]]:
        out = []
        for i, count in enumerate(self.counts):
            le = self.bounds[i] if i < len(self.bounds) else None  # +Inf
            out.append({"le": le, "count": count,
                        "exemplar": self.exemplars[i]})
        return out


class _ObjectiveState:
    __slots__ = ("objective", "window", "bad", "histogram",
                 "attribution", "last_breach_t", "breaches")

    def __init__(self, objective: SLObjective, buckets):
        self.objective = objective
        # (t, bad, value) — pruned past window_s on observe and read.
        self.window: "collections.deque[Tuple[float, bool, float]]" = (
            collections.deque())
        self.bad = 0
        self.histogram = (ExemplarHistogram(buckets)
                          if objective.kind == "latency" else None)
        # Per-stage seconds accumulated over VIOLATING requests only:
        # where the time of the bad tail actually went.
        self.attribution: Dict[str, float] = {}
        self.last_breach_t: Optional[float] = None
        self.breaches = 0

    def prune(self, now: float) -> None:
        horizon = now - self.objective.window_s
        w = self.window
        while w and w[0][0] <= horizon:
            _, was_bad, _ = w.popleft()
            if was_bad:
                self.bad -= 1


class SLOEvaluator:
    """Sliding-window burn-rate evaluator over per-request completions
    (see module doc). Thread-safe; observation is O(1) amortized."""

    def __init__(
        self,
        objectives: Sequence,
        metrics=None,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
        on_breach: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        breach_cooldown_s: float = 60.0,
        exemplar_buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.objectives = parse_slos(objectives)
        self.clock = clock
        self.on_breach = on_breach
        self.breach_cooldown_s = float(breach_cooldown_s)
        self._states = {o.name: _ObjectiveState(o, exemplar_buckets)
                        for o in self.objectives}
        self._lock = threading.Lock()
        self._tele = telemetry
        self._burn_g = {}
        if metrics is not None:
            self._burn_g = {o.name: metrics.gauge("slo_burn_rate",
                                                  objective=o.name)
                            for o in self.objectives}

    def __bool__(self) -> bool:
        return bool(self.objectives)

    # --------------------------------------------------------- feeding

    def observe(self, outcome: str, e2e_s: float,
                stages: Optional[Dict[str, float]] = None,
                request_id: Optional[str] = None,
                now: Optional[float] = None) -> None:
        """One completed request. `stages` (from a RequestTrace, may be
        None when tracing is off) powers per-stage objectives and the
        violation attribution; burn math needs only outcome + e2e."""
        if now is None:
            now = self.clock()
        breaches = []
        with self._lock:
            for name, st in self._states.items():
                o = st.objective
                if o.kind == "latency":
                    if outcome not in _LATENCY_OUTCOMES:
                        continue
                    if o.stage == "e2e":
                        value = e2e_s
                    else:
                        # A stage objective with no stage measurement
                        # (tracing off, or the request never reached
                        # that stage) SKIPS rather than silently
                        # judging e2e against a stage threshold.
                        value = (stages or {}).get(o.stage)
                        if value is None:
                            continue
                    bad = (value > o.threshold_s
                           or outcome in o.bad_outcomes)
                    if st.histogram is not None:
                        st.histogram.observe(value, request_id, now)
                else:  # error_rate
                    value = e2e_s
                    bad = outcome in o.bad_outcomes
                st.window.append((now, bad, value))
                if bad:
                    st.bad += 1
                    if stages:
                        for stage, dur in stages.items():
                            st.attribution[stage] = (
                                st.attribution.get(stage, 0.0) + dur)
                st.prune(now)
                burn = self._burn_locked(st)
                gauge = self._burn_g.get(name)
                if gauge is not None:
                    gauge.set(burn)
                if burn > 1.0 and (
                        st.last_breach_t is None
                        or now - st.last_breach_t
                        >= self.breach_cooldown_s):
                    st.last_breach_t = now
                    st.breaches += 1
                    breaches.append((name, self._status_locked(st, now)))
        # Breach actions run OUTSIDE the lock: an on_breach that blocks
        # (profile capture) must not stall concurrent observers.
        for name, status in breaches:
            if self._tele is not None:
                self._tele.emit(
                    "slo_breach", objective=name,
                    burn_rate=status["burn_rate"],
                    window_s=status["window_s"], bad=status["bad"],
                    total=status["total"],
                    bad_fraction=status["bad_fraction"],
                    attribution=status["attribution"])
            if self.on_breach is not None:
                try:
                    self.on_breach(name, status)
                except Exception:
                    logger.warning("slo on_breach action failed",
                                   exc_info=True)

    # --------------------------------------------------------- reading

    def _burn_locked(self, st: _ObjectiveState) -> float:
        total = len(st.window)
        if not total:
            return 0.0
        return (st.bad / total) / st.objective.budget

    def _status_locked(self, st: _ObjectiveState,
                       now: float) -> Dict[str, Any]:
        st.prune(now)
        o = st.objective
        total = len(st.window)
        burn = self._burn_locked(st)
        out: Dict[str, Any] = {
            "kind": o.kind, "target": o.target, "window_s": o.window_s,
            "total": total, "bad": st.bad,
            "bad_fraction": round(st.bad / total, 6) if total else 0.0,
            "burn_rate": round(burn, 6),
            "breached": burn > 1.0,
            "breaches_total": st.breaches,
            "attribution": {k: round(v, 6)
                            for k, v in sorted(st.attribution.items())},
        }
        if o.kind == "latency":
            out["threshold_s"] = o.threshold_s
            out["stage"] = o.stage
            if st.histogram is not None:
                out["histogram"] = st.histogram.snapshot()
        return out

    def burn_rate(self, name: str, now: Optional[float] = None) -> float:
        if now is None:
            now = self.clock()
        with self._lock:
            st = self._states[name]
            st.prune(now)
            return self._burn_locked(st)

    def refresh_gauges(self, now: Optional[float] = None) -> None:
        """Re-prune every window and re-set the burn gauges: called at
        scrape/stats time so an idle stream's gauge decays with the
        window instead of freezing at the last observed burn."""
        if now is None:
            now = self.clock()
        with self._lock:
            for name, st in self._states.items():
                st.prune(now)
                gauge = self._burn_g.get(name)
                if gauge is not None:
                    gauge.set(self._burn_locked(st))

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """{objective name: status dict} — the Server.stats()["slo"]
        and `pbt diagnose --serve` payload. Also refreshes the burn
        gauges (prune-at-read): stats() and /metrics agree."""
        if now is None:
            now = self.clock()
        with self._lock:
            out = {}
            for name, st in self._states.items():
                out[name] = self._status_locked(st, now)
                gauge = self._burn_g.get(name)
                if gauge is not None:
                    gauge.set(out[name]["burn_rate"])
            return out


class ProfileTrigger:
    """`on_breach` action: capture a short on-demand device profile.

    Starts `jax.profiler.start_trace(directory)` and stops it from a
    timer thread after `duration_s`; at most one capture per
    `cooldown_s` and never more than one in flight. All failure modes
    (jax absent, profiler already active, full disk) log and return —
    an SLO breach must never take the server down with it."""

    def __init__(self, directory: str, duration_s: float = 2.0,
                 cooldown_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic,
                 start=None, stop=None):
        self.directory = directory
        self.duration_s = float(duration_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._start = start
        self._stop = stop
        self._lock = threading.Lock()
        self._active = False
        self._last_t: Optional[float] = None
        self.captures: List[Dict[str, Any]] = []

    def _profiler(self):
        jax = sys.modules.get("jax")
        return None if jax is None else getattr(jax, "profiler", None)

    def __call__(self, objective: str, status: Dict[str, Any]) -> None:
        now = self.clock()
        with self._lock:
            if self._active:
                return
            if self._last_t is not None \
                    and now - self._last_t < self.cooldown_s:
                return
            start = self._start
            stop = self._stop
            if start is None or stop is None:
                prof = self._profiler()
                if prof is None:
                    logger.info("slo breach on %r but jax is not live; "
                                "skipping device profile", objective)
                    return
                start = start or prof.start_trace
                stop = stop or prof.stop_trace
            try:
                start(self.directory)
            except Exception:
                logger.warning("slo breach profile capture failed to "
                               "start", exc_info=True)
                return
            self._active = True
            self._last_t = now
            self.captures.append({"objective": objective, "t": now,
                                  "directory": self.directory})
        logger.warning("slo breach on %r (burn %.2f): capturing %.1fs "
                       "device profile to %s", objective,
                       status.get("burn_rate", 0.0), self.duration_s,
                       self.directory)

        def _finish():
            try:
                stop()
            except Exception:
                logger.warning("slo breach profile capture failed to "
                               "stop", exc_info=True)
            finally:
                with self._lock:
                    self._active = False

        timer = threading.Timer(self.duration_s, _finish)
        timer.daemon = True
        timer.start()
