"""Config system for the TPU build.

The reference has no config system at all — every hyperparameter is a
constant in the smoke driver (reference dummy_tests.py:16-19,102-141) or a
kwarg default (reference utils.py:220-231, modules.py:243-245). Here the
whole framework is driven by one frozen dataclass tree so configs hash, are
jit-static-friendly, and carry the tiny/base/long/large presets from
BASELINE.json.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture of the dual-track ProteinBERT model.

    Defaults mirror the reference smoke config (reference dummy_tests.py:
    110-118: seq_len 256, local 128, global 512, key 64, 4 heads, 6 blocks)
    but the model here is shape-parametric in seq_len (the reference's
    LayerNorm hard-codes L at construction, modules.py:148-151 — fixed).
    """

    vocab_size: int = 26                # 22 AA chars + 4 specials (data/vocab.py)
    num_annotations: int = 8943         # GO terms with >=100 records (SURVEY C3)
    local_dim: int = 128                # local (per-residue) channel dim C
    global_dim: int = 512               # global (per-protein) dim G
    key_dim: int = 64                   # attention key dim per head
    num_heads: int = 4                  # global-attention heads
    num_blocks: int = 6                 # dual-track blocks
    narrow_kernel: int = 9              # narrow Conv1d kernel (modules.py:126)
    wide_kernel: int = 9                # wide Conv1d kernel (modules.py:137)
    wide_dilation: int = 5              # wide Conv1d dilation (modules.py:141)
    dtype: str = "bfloat16"             # activation dtype (MXU-native)
    param_dtype: str = "float32"        # parameter dtype
    remat: bool = False                 # jax.checkpoint each block
    remat_policy: str = "full"          # "full" (recompute everything) |
                                        # "convs" (save the two conv outputs
                                        # per block — ~85% of block FLOPs —
                                        # and recompute only the cheap tail)
    scan_blocks: bool = True            # lax.scan over stacked block params
    scan_unroll: int = 1                # lax.scan unroll factor: XLA sees k
                                        # block bodies per iteration and can
                                        # keep activation layouts across
                                        # them (the scan-boundary transposes
                                        # are a measured cost,
                                        # docs/performance.md); full unroll
                                        # (scan_blocks=False) is compile-
                                        # prohibitive at real sizes
    scan_split_transpose: bool = False  # lax.scan(_split_transpose=True):
                                        # transpose the block scan as two
                                        # passes (recompute-forward, then
                                        # grad sweep) so XLA can schedule
                                        # the saves' layout traffic
                                        # separately from the grad math —
                                        # an experimental alternative lever
                                        # on the same measured scan-
                                        # boundary cost scan_unroll targets
    use_pallas: bool = False            # Pallas fused local-track kernel

    @property
    def value_dim(self) -> int:
        # reference modules.py:119: value_dim = global_dim // num_heads
        assert self.global_dim % self.num_heads == 0
        return self.global_dim // self.num_heads


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Online pipeline: tokenization + denoising corruption.

    Probabilities follow the reference corruption pipeline (reference
    data_processing.py:86-142), with the hide-all-annotations branch kept as
    an explicit knob (SURVEY ledger #5).
    """

    seq_len: int = 256                      # fixed padded length fed to the model
    buckets: Optional[Tuple[int, ...]] = None  # length buckets (last == seq_len);
                                            # None = single padded length
    packing: bool = False                   # segment-aware sequence packing
                                            # (data/packing.py): several
                                            # proteins per fixed-shape row
                                            # with segment ids — ONE compiled
                                            # shape, ~zero pad FLOPs; mutually
                                            # exclusive with buckets
    pack_max_segments: int = 8              # max proteins per packed row (the
                                            # S axis of the per-segment
                                            # annotation tensor)
    pack_open_bins: int = 0                 # packer look-back: open rows the
                                            # first-fit planner keeps before
                                            # closing the oldest (0 = auto,
                                            # 2 x global batch)
    token_randomize_prob: float = 0.05      # data_processing.py:90
    annotation_corrupt_prob: float = 0.5    # P(keep-and-noise); else hide all
                                            # (data_processing.py:127-128)
    annotation_drop_prob: float = 0.25      # drop positives (data_processing.py:116)
    annotation_add_prob: float = 1e-4       # add false positives (:117)
    batch_size: int = 32
    prefetch_depth: int = 2                 # host batches produced ahead on a
                                            # background thread (0 = off)
    num_epochs: Optional[int] = None        # bound the data stream; None =
                                            # loop forever (iteration-based,
                                            # like the reference)


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Adam + warmup schedule (reference dummy_tests.py:127-130, utils.py:257-264).

    The reference chains LambdaLR warmup into ReduceLROnPlateau via
    SequentialLR, which crashes after warmup (SURVEY ledger #7). Here both a
    correct warmup+plateau and warmup+cosine are provided.
    """

    learning_rate: float = 2e-4             # dummy_tests.py:128
    warmup_steps: int = 10_000              # utils.py:233 warmup_duration
    schedule: str = "warmup_plateau"        # "warmup_plateau" | "warmup_cosine" | "constant"
    total_steps: int = 100_000              # cosine horizon
    plateau_window: int = 100               # steps averaged into ONE plateau
                                            # observation (set ≈ eval_every so
                                            # the signal tracks eval cadence,
                                            # not per-step batch noise)
    plateau_patience: int = 10              # windowed observations without
                                            # improvement before LR is cut
    plateau_factor: float = 0.1             # plateau: LR multiplier on trigger
    plateau_cooldown: int = 10              # observations to ignore after a cut
                                            # (lets the loss re-baseline before
                                            # another reduction can chain)
    plateau_metric: str = "train_loss"      # "train_loss" | "eval_loss" — what
                                            # reduce_on_plateau observes. The
                                            # reference intended a METRIC-driven
                                            # ReduceLROnPlateau (utils.py:257-264
                                            # — it crashed); "eval_loss" feeds
                                            # the latest cadenced held-out loss
                                            # to the transform every step, so an
                                            # eval-only regime shift (train loss
                                            # falling while eval rises — the
                                            # r3 sustained run) CAN cut the LR.
                                            # Set plateau_window ≈ eval_every so
                                            # one windowed observation covers one
                                            # eval interval; requires eval_every
                                            # > 0 and an eval split. The trainer
                                            # seeds the stream with an up-front
                                            # eval bracket so the plateau window
                                            # never mixes train-scale values
                                            # (ADVICE r4).
    grad_clip_norm: float = 1.0             # reference clips grads (utils.py:136)
    b1: float = 0.9
    b2: float = 0.999
    weight_decay: float = 0.0


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh axes — entirely new vs the reference (SURVEY C18: absent).

    Axes: data (DP), fsdp (param/optimizer sharding over data axis), model
    (TP over global/annotation dims), seq (sequence parallelism for the
    local conv track with halo exchange).
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("data", "fsdp", "model", "seq")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.model, self.seq)

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Cross-replica execution strategy knobs (beyond the mesh SHAPE,
    which stays in MeshConfig).

    zero_update: ZeRO-1 sharded weight update (Xu et al.,
      arXiv:2004.13336). The pure `data` axis normally replicates fp32
      params and Adam mu/nu on every replica and pays a full gradient
      all-reduce per step; with zero_update the train step
      reduce-scatters gradients over ('data','fsdp'), applies the
      optimizer to a 1/(data*fsdp) shard, and all-gathers the updated
      params — Adam state HBM drops by ~(1 - 1/data_extent) on top of
      fsdp, for near-equal total collective bytes (reduce-scatter +
      all-gather ≈ all-reduce). Sharded-optimizer storage lives in
      parallel/sharding.py (zero-aware state_sharding); the update
      itself in parallel/zero.py. No-op without a mesh or when
      data*fsdp == 1.
    grad_reduce_dtype: payload dtype of the ZeRO-1 gradient reduction
      — "fp32" (exact, the implicit-SPMD reduce-scatter), or "bf16" /
      "int8": the QUANTIZED reduce-scatter (parallel/quant.py,
      EQuARX-style, arXiv:2506.17615). The quantized step computes
      per-replica partial gradients inside an explicit data-parallel
      shard_map and reduces them over quantized payloads — bf16
      (stochastic rounding, 2x fewer wire bytes) or int8 (per-chunk
      symmetric scale + stochastic rounding seeded from the step key:
      deterministic and multi-host lockstep, ~4x fewer wire bytes) —
      with the optimizer math fp32 on the dequantized shards and the
      clip norm measured on the dequantized sum. Wire bytes are
      verified from compiled HLO (bench.py --comm,
      zero.collective_wire_bytes_from_hlo); parity bounds are measured
      in tests/test_quant.py and documented in docs/distributed.md.
      Quantized payloads need a data/fsdp-only mesh (model>1 or seq>1
      raises the typed QuantConfigError — the explicit replica
      shard_map cannot shard those axes), a global batch divisible by
      data*fsdp, and are rejected by the explicit seq-parallel Pallas
      step (int8; its bf16 stays the PR-2 cast-only numerics-only
      reduction). Only consulted by the zero_update path.
    """

    zero_update: bool = False
    grad_reduce_dtype: str = "fp32"         # "fp32" | "bf16" | "int8"


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online-serving knobs that belong to the MODEL's run config (the
    CLI owns transport knobs like ports and queue depths; these ride
    config.json so `pbt serve --pretrained RUN_DIR` picks them up).

    quant: which executable arm the dispatcher builds (parallel/
      quant.py) — "fp32" (ordinary), "int8" (symmetric per-channel
      int8 WEIGHTS quantized at load time, dequantized inside the
      executable: ~4x smaller resident trunk — the HBM headroom two
      resident trunks need), or "int8_act" (int8 weights + opt-in
      dynamic int8 fake-quant of the trunk's output activations).
      Overridable per serve process via `pbt serve --quant`.
    quant_parity_every: with a quantized arm, every Nth dispatched
      batch ALSO runs the fp32 executables on the same inputs and
      records the per-request max-abs output deviation
      (`serve_quant_parity_max` gauge, stats()["quant"], serve_batch
      events) — live parity evidence at 1/N the cost. 0 disables.
    pipeline_depth: bounded in-flight window for pipelined dispatch
      (ISSUE 19): the scheduler submits up to this many batches before
      blocking, and a completer thread resolves device results while
      the next batch forms — device compute overlaps host fetch +
      fan-out. 1 disables the completer and restores the serial
      submit-then-finalize path bit-for-bit. Overridable per serve
      process via `pbt serve --pipeline-depth`.
    """

    quant: str = "fp32"                     # "fp32" | "int8" | "int8_act"
    quant_parity_every: int = 0
    pipeline_depth: int = 2


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Checkpoint cadence (reference utils.py:227 nb_iterations_checkpoint=1000)."""

    directory: str = "checkpoints"
    every_steps: int = 1000
    max_to_keep: int = 3
    async_save: bool = True
    overlap: bool = True                    # overlapped boundary: snapshot
                                            # the state on device and run
                                            # the device→host fetch + save
                                            # on a stager thread while the
                                            # train stream keeps
                                            # dispatching — the boundary
                                            # costs ~zero wall time instead
                                            # of drain→fetch→save
                                            # (single-process runs only;
                                            # multi-host falls back to the
                                            # synchronous collective save)
    warm_start: bool = False                # save once at the start step,
                                            # BEFORE the perf timer anchors:
                                            # pays orbax setup + the first
                                            # full device->host fetch up
                                            # front, so the first cadenced
                                            # save's one-time cost cannot
                                            # land in the timed stream (the
                                            # r3 collapse's 650-800 stretch,
                                            # BASELINE.md round-5
                                            # attribution)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Iteration-based pretraining loop config (reference utils.py:220-231)."""

    max_steps: int = 250                    # dummy_tests.py:141 smoke default
    log_every: int = 10
    eval_every: int = 0                     # 0 = no eval
    on_nan: str = "halt"                    # "halt" | "warn" | "off" — NaN/Inf
                                            # watch on logged loss/grad_norm
                                            # (train/resilience.py)
    early_stop_patience: int = 0            # consecutive cadenced evals without
                                            # eval_loss improvement before the
                                            # run checkpoints and stops; 0 = off.
                                            # The best/stalled counters (and the
                                            # latest eval loss the eval-keyed
                                            # plateau observes) are CHECKPOINTED
                                            # with the data position, so a
                                            # preempt/requeue loop cannot reset
                                            # the patience baseline.
    early_stop_min_delta: float = 0.0       # improvement smaller than this
                                            # still counts as a stall
    overlap_eval: bool = True               # dispatch the periodic eval
                                            # bracket asynchronously and
                                            # resolve its metrics after the
                                            # next train step has been
                                            # dispatched, instead of a
                                            # synchronous fetch-per-batch
                                            # bracket. Applied only where
                                            # legal: an eval-keyed plateau
                                            # or early stopping needs the
                                            # eval value BEFORE the next
                                            # step and keeps the
                                            # synchronous bracket.
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class TaskConfig:
    """A supervised fine-tuning task on the pretrained trunk (SURVEY C14 —
    the reference's fine-tune harness exists only as commented-out code,
    reference utils.py:348-493; completed here).

    Kinds (the ProteinBERT paper's benchmark shapes):
      token_classification  — per-residue labels (secondary structure);
      sequence_classification — per-protein label (remote homology);
      sequence_regression   — per-protein scalar (stability, fluorescence).
    """

    kind: str = "token_classification"
    num_outputs: int = 8                # classes, or 1 for regression
    freeze_trunk: bool = False          # train head only
    head_hidden_dim: int = 0            # 0 = linear head, else one MLP layer
    epochs: int = 10
    eval_every_epochs: int = 1


@dataclasses.dataclass(frozen=True)
class FinetuneConfig:
    model: "ModelConfig" = dataclasses.field(default_factory=lambda: ModelConfig())
    task: TaskConfig = dataclasses.field(default_factory=TaskConfig)
    data: "DataConfig" = dataclasses.field(default_factory=lambda: DataConfig())
    optimizer: "OptimizerConfig" = dataclasses.field(
        default_factory=lambda: OptimizerConfig(
            learning_rate=1e-4, warmup_steps=100, schedule="warmup_cosine",
            total_steps=10_000,
        )
    )
    checkpoint: "CheckpointConfig" = dataclasses.field(
        default_factory=lambda: CheckpointConfig(directory="finetune_checkpoints")
    )
    train: "TrainConfig" = dataclasses.field(default_factory=lambda: TrainConfig())

    def replace(self, **kw) -> "FinetuneConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class PretrainConfig:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    checkpoint: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)

    def replace(self, **kw) -> "PretrainConfig":
        return dataclasses.replace(self, **kw)


def _tiny() -> PretrainConfig:
    # BASELINE.json configs[0]: 2 blocks, d=128, seq_len=128 — CPU smoke.
    return PretrainConfig(
        model=ModelConfig(local_dim=32, global_dim=128, key_dim=32, num_heads=4,
                          num_blocks=2, num_annotations=512, dtype="float32"),
        data=DataConfig(seq_len=128, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=50, total_steps=250),
        train=TrainConfig(max_steps=250),
    )


def _base() -> PretrainConfig:
    # BASELINE.json configs[1]: 6 blocks, d=512, seq_len=512 — v5e-16 DP.
    # remat on: the scan otherwise saves fp32 LN intermediates for all 6
    # blocks (~12G at batch 128 on a 16G chip) and is HBM-bound; measured
    # on v5e-1 remat is BOTH smaller and faster (MFU 0.52 vs 0.39), and
    # the "convs" policy (save conv outputs, recompute the cheap tail)
    # another +8% over full remat (MFU 0.56, BASELINE.md).
    return PretrainConfig(
        model=ModelConfig(local_dim=512, global_dim=512, key_dim=64, num_heads=8,
                          num_blocks=6, remat=True, remat_policy="convs"),
        data=DataConfig(seq_len=512, batch_size=128),
        optimizer=OptimizerConfig(warmup_steps=10_000, total_steps=1_000_000),
        train=TrainConfig(max_steps=1_000_000),
        mesh=MeshConfig(data=16),
    )


def _long() -> PretrainConfig:
    # BASELINE.json configs[2]: seq_len=2048 long-context, sequence-parallel,
    # length-bucketed (most UniRef sequences are far shorter than 2048).
    return PretrainConfig(
        model=ModelConfig(local_dim=512, global_dim=512, key_dim=64, num_heads=8,
                          num_blocks=6, remat=True, remat_policy="convs"),
        data=DataConfig(seq_len=2048, batch_size=64,
                        buckets=(512, 1024, 2048)),
        optimizer=OptimizerConfig(warmup_steps=10_000, total_steps=1_000_000),
        train=TrainConfig(max_steps=1_000_000),
        mesh=MeshConfig(data=4, seq=4),
    )


def _large() -> PretrainConfig:
    # BASELINE.json configs[4]: 12 blocks, d=1024, full 8943-dim GO head.
    return PretrainConfig(
        model=ModelConfig(local_dim=1024, global_dim=1024, key_dim=64,
                          num_heads=16, num_blocks=12, remat=True,
                          remat_policy="convs"),
        data=DataConfig(seq_len=1024, batch_size=256),
        optimizer=OptimizerConfig(warmup_steps=10_000, total_steps=2_000_000),
        train=TrainConfig(max_steps=2_000_000),
        mesh=MeshConfig(data=64, model=4),
    )


PRESETS = {
    "tiny": _tiny,
    "base": _base,
    "long": _long,
    "large": _large,
}


def get_preset(name: str) -> PretrainConfig:
    try:
        return PRESETS[name]()
    except KeyError:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}") from None


def config_to_dict(cfg) -> dict:
    """Frozen config tree → plain JSON-serializable dict (tuples become
    lists; from_dict restores them)."""
    return dataclasses.asdict(cfg)


def _build(cls, data: dict):
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        if isinstance(v, dict):
            # Nested config: resolve the node class from the field's
            # default (f.type is a string under PEP 563 annotations).
            default = (f.default_factory() if f.default_factory
                       is not dataclasses.MISSING else f.default)
            kwargs[f.name] = _build(type(default), v)
        elif isinstance(v, list):
            kwargs[f.name] = tuple(v)  # configs must stay hashable
        else:
            kwargs[f.name] = v
    return cls(**kwargs)


def config_from_dict(data: dict, cls=None):
    """Inverse of config_to_dict. `cls` defaults to PretrainConfig."""
    return _build(cls or PretrainConfig, data)


def save_config(cfg, path: str) -> None:
    """Write the config as JSON (pretrain drops one into the run dir so
    downstream commands need no repeated --pretrained-set flags).

    Atomic (temp file + rename): a crash mid-write must not leave a
    truncated config.json that poisons every later --pretrained consumer
    of an otherwise-valid run dir."""
    import json
    import os
    import tempfile

    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp",
                               dir=os.path.dirname(os.path.abspath(path)))
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(config_to_dict(cfg), f, indent=2, sort_keys=True)
        os.chmod(tmp, 0o644)  # mkstemp is 0600
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_config(path: str, cls=None):
    import json

    with open(path) as f:
        return config_from_dict(json.load(f), cls)
