from proteinbert_tpu.configs.config import (
    CheckpointConfig,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    PretrainConfig,
    TrainConfig,
    get_preset,
    PRESETS,
)

__all__ = [
    "CheckpointConfig",
    "DataConfig",
    "MeshConfig",
    "ModelConfig",
    "OptimizerConfig",
    "PretrainConfig",
    "TrainConfig",
    "get_preset",
    "PRESETS",
]
