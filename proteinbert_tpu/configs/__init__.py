from proteinbert_tpu.configs.config import (
    CheckpointConfig,
    DataConfig,
    FinetuneConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    PretrainConfig,
    TaskConfig,
    TrainConfig,
    get_preset,
    PRESETS,
)

__all__ = [
    "CheckpointConfig",
    "DataConfig",
    "FinetuneConfig",
    "MeshConfig",
    "ModelConfig",
    "OptimizerConfig",
    "PretrainConfig",
    "TaskConfig",
    "TrainConfig",
    "get_preset",
    "PRESETS",
]
