"""Timestamped logging (reference C20: shared_utils/util.py:25-54, redone).

The reference maintains two parallel logging systems — a vendored `log()`
writing to stdout + an optional pid-stamped file, and stdlib `logging`
configured by the driver. Here there is ONE: `log()` forwards into a
stdlib logger (`proteinbert_tpu`), and `start_log()` attaches the
timestamped stream/file handlers. Everything composes with user logging
config instead of fighting it.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LOGGER = logging.getLogger("proteinbert_tpu")
_FMT = "[%(asctime)s] %(message)s"


def log(message, level: int = logging.INFO, **_ignored) -> None:
    """Timestamped log line (reference shared_utils/util.py:25-40)."""
    if not _LOGGER.handlers and not logging.getLogger().handlers:
        start_log()
    _LOGGER.log(level, message)


def start_log(
    log_dir: Optional[str] = None,
    log_file_prefix: str = "log",
    pid_stamp: bool = True,
    level: int = logging.INFO,
) -> Optional[str]:
    """Attach stream (+ optional pid-stamped file) handlers (reference
    shared_utils/util.py:43-54). Returns the log-file path if any."""
    _LOGGER.setLevel(level)
    _LOGGER.propagate = False
    if not any(isinstance(h, logging.StreamHandler) and not
               isinstance(h, logging.FileHandler) for h in _LOGGER.handlers):
        sh = logging.StreamHandler(sys.stdout)
        sh.setFormatter(logging.Formatter(_FMT))
        _LOGGER.addHandler(sh)
    path = None
    if log_dir is not None:
        os.makedirs(log_dir, exist_ok=True)
        name = (f"{log_file_prefix}.{os.getpid()}.log" if pid_stamp
                else f"{log_file_prefix}.log")
        path = os.path.abspath(os.path.join(log_dir, name))
        # Idempotent: a repeated start_log() with the same log_dir must
        # not attach a SECOND FileHandler for the same file (every line
        # was written twice per extra call — e.g. cli main()'s start_log
        # followed by a library consumer calling it again).
        if not any(isinstance(h, logging.FileHandler)
                   and getattr(h, "baseFilename", None) == path
                   for h in _LOGGER.handlers):
            fh = logging.FileHandler(path)
            fh.setFormatter(logging.Formatter(_FMT))
            _LOGGER.addHandler(fh)
    return path
