"""jax version-compat shims, in ONE place.

Two classes of fix accumulated across the harness while making the suite
run on both jax 0.4.x and >= 0.5:

- `request_cpu_devices(n)`: force n virtual CPU devices before the
  backend initializes. jax >= 0.5 has a first-class
  `jax_num_cpu_devices` config option that works even when env vars were
  read before the caller ran (images whose sitecustomize imports jax at
  interpreter start); jax 0.4.x only has the
  `--xla_force_host_platform_device_count` XLA flag, which works as long
  as the CPU backend has not been created yet (XLA reads the env var at
  client creation, not module import). This helper was previously
  duplicated — with drifting except-clauses — across tests/conftest.py,
  the multi-device/multi-host child scripts, and __graft_entry__.py.

- `shard_map(...)`: top-level `jax.shard_map` with the `check_vma` kwarg
  on jax >= 0.6; on 0.4.x the function lives in
  jax.experimental.shard_map and the varying-mesh-axes checker flag is
  spelled `check_rep`. (Moved here from parallel/mesh.py, which
  re-exports it for existing importers.)
"""

from __future__ import annotations

import os
import re

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> bool:
    """Ask for `n` virtual CPU devices; call BEFORE any device use.

    Returns True when the jax >= 0.5 config API took, False when the
    0.4.x XLA_FLAGS fallback was installed instead. Either way the
    caller should verify `jax.device_count()` afterwards — on an
    already-initialized backend neither mechanism can take effect
    (the config API raises RuntimeError, swallowed here so a dry run
    inside a warm session degrades to the caller's count check instead
    of crashing)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:  # backend already initialized
        pass
    try:
        jax.config.update("jax_num_cpu_devices", n)
        return True
    except AttributeError:
        # jax 0.4.x: env route. Replace any previous count rather than
        # appending a duplicate (last flag wins in XLA, but a child that
        # scrubs flags by regex must see exactly one).
        flags = scrub_device_count_flag(os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = f"{flags} {_FORCE_FLAG}={n}".strip()
        return False
    except RuntimeError:
        return True


def scrub_device_count_flag(flags: str) -> str:
    """Remove any --xla_force_host_platform_device_count=N from an
    XLA_FLAGS string. Test parents pinned to 8 devices use this on a
    child's env so the child's own request_cpu_devices(n) is the only
    count in play — one definition here, next to the code that re-adds
    the flag, so the two can't drift."""
    return re.sub(_FORCE_FLAG + r"=\d+", "", flags).strip()


def has_num_cpu_devices_option() -> bool:
    """True on jax >= 0.5 (first-class jax_num_cpu_devices option).

    Doubles as the harness's version sentinel for the 0.4.x
    CPU-persistent-cache/donation bug (tests/conftest.py, bench.py)."""
    import jax

    return hasattr(jax.config, "jax_num_cpu_devices")


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """Version-compat shard_map; `check_vma=None` means "the version's
    default" (0.4.x spells the checker flag `check_rep`)."""
    import jax

    try:
        sm = jax.shard_map
        kw = {} if check_vma is None else {"check_vma": check_vma}
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

        kw = {} if check_vma is None else {"check_rep": check_vma}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def configure_compile_cache(directory: str) -> None:
    """Arm the persistent XLA compilation cache rooted at `directory`
    (`pbt serve --compile-cache-dir`, fleet replicas): restarted or
    newly spawned replicas deserialize their warm executables instead
    of re-paying the per-kind compile, so a replacement replica boots
    in cache-load time, not warmup time (the saving is visible in the
    `serve_warmup_seconds_total` gauge across boots —
    tests/test_fleet.py asserts the second boot is faster).

    Min-compile-time is forced to 0 so EVERY serve executable caches
    (serving shapes are small; the default threshold would skip them).
    Must run before the first compile of the process — the CLI calls it
    before the trunk loads."""
    import jax

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
