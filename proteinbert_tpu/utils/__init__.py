"""Host-side utilities: logging, profiling, ETL sharding, h5 helpers,
stats (reference C17/C20/C21/C22, rebuilt — see each module's docstring)."""

from proteinbert_tpu.utils.logging import log, start_log
from proteinbert_tpu.utils.profiling import (
    Profiler,
    TimeMeasure,
    device_memory_report,
    device_trace,
    monitor_memory,
)
from proteinbert_tpu.utils.stats import (
    benjamini_hochberg,
    benjamini_hochberg_with_nulls,
    drop_redundant_columns,
    fisher_enrichment,
    liftover_positions,
    manhattan_plot,
    one_hot,
    write_excel,
)
from proteinbert_tpu.utils.sharding import (
    all_shard_file_names,
    shard_file_name,
    shard_items,
    shard_range,
    task_identity,
    to_chunks,
)

__all__ = [
    "log", "start_log",
    "Profiler", "TimeMeasure", "device_trace",
    "monitor_memory", "device_memory_report",
    "to_chunks", "shard_range", "shard_items", "task_identity",
    "shard_file_name", "all_shard_file_names",
    "benjamini_hochberg", "benjamini_hochberg_with_nulls",
    "drop_redundant_columns", "fisher_enrichment",
    "one_hot", "manhattan_plot",
    "write_excel", "liftover_positions",
]
