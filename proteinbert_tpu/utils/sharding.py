"""CPU-side job sharding for the ETL (reference C17, scheduler-agnostic).

The reference's only "distributed" machinery is SLURM task-array plumbing
for embarrassing ETL parallelism (reference shared_utils/util.py:243-297,
436-505, 1121-1157). Here the same capability is one small function pair:
`task_identity()` reads whichever scheduler's env vars are present (SLURM
array vars, or the generic TASK_INDEX/TASK_COUNT, with an optional offset
and explicit CLI override), and `shard_range`/`to_chunks` do the index
math. Model-training distribution is NOT here — that is jax collectives
(parallel/), a different axis entirely.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple


def to_chunks(items: Iterable, chunk_size: int) -> Iterator[list]:
    """Yield lists of up to `chunk_size` items (reference
    shared_utils/util.py:257-269)."""
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    chunk: list = []
    for item in items:
        chunk.append(item)
        if len(chunk) >= chunk_size:
            yield chunk
            chunk = []
    if chunk:
        yield chunk


def shard_range(n: int, shard_index: int, num_shards: int) -> Tuple[int, int]:
    """[start, end) of shard `shard_index` when n items are split as
    evenly as possible (first n % num_shards shards get one extra)."""
    if not 0 <= shard_index < num_shards:
        raise ValueError(f"shard {shard_index} outside [0, {num_shards})")
    base, extra = divmod(n, num_shards)
    start = shard_index * base + min(shard_index, extra)
    return start, start + base + (1 if shard_index < extra else 0)


def shard_items(items: Sequence, shard_index: int, num_shards: int) -> Sequence:
    lo, hi = shard_range(len(items), shard_index, num_shards)
    return items[lo:hi]


def task_identity(
    task_index: Optional[int] = None,
    task_count: Optional[int] = None,
) -> Tuple[int, int]:
    """(task_index, task_count) for this ETL worker.

    Precedence: explicit args → SLURM array env (SLURM_ARRAY_TASK_ID /
    _COUNT, with TASK_ID_OFFSET applied as in reference
    shared_utils/util.py:1126-1145) → generic TASK_INDEX/TASK_COUNT env →
    (0, 1) standalone.
    """
    if task_index is not None or task_count is not None:
        if task_index is None or task_count is None:
            raise ValueError("give both task_index and task_count or neither")
        if not 0 <= task_index < task_count:
            raise ValueError(f"task {task_index} outside [0, {task_count})")
        return task_index, task_count

    if "SLURM_ARRAY_TASK_ID" in os.environ:
        idx = int(os.environ["SLURM_ARRAY_TASK_ID"])
        idx += int(os.environ.get("TASK_ID_OFFSET", 0))
        count = int(os.environ.get("SLURM_ARRAY_TASK_COUNT", 0))
        if count <= 0:
            raise ValueError(
                "SLURM_ARRAY_TASK_ID set but SLURM_ARRAY_TASK_COUNT missing")
        return idx, count

    if "TASK_INDEX" in os.environ:
        return int(os.environ["TASK_INDEX"]), int(os.environ.get("TASK_COUNT", 1))

    return 0, 1


def shard_file_name(path: str, shard_index: int, num_shards: int) -> str:
    """foo.db → foo.shard3of8.db (identity when num_shards == 1)."""
    if num_shards == 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.shard{shard_index}of{num_shards}{ext}"


def all_shard_file_names(path: str, num_shards: int) -> List[str]:
    return [shard_file_name(path, i, num_shards) for i in range(num_shards)]
