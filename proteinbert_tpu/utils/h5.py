"""Out-of-core HDF5 helpers (reference C21 parity).

The reference vendors a chunked matrix transpose with fsync flushes
(shared_utils/util.py:591-615, 941-951) used to reorient big feature
matrices without loading them. Kept here with a cleaner loop, plus the
small numpy helpers the ETL path actually uses.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def flush_h5_file(h5f) -> None:
    """Flush library buffers AND fsync the OS file (reference
    shared_utils/util.py:948-951) so a crash mid-ETL loses one chunk at
    most."""
    h5f.flush()
    fd = h5f.id.get_vfd_handle()
    if isinstance(fd, int):
        os.fsync(fd)


def transpose_dataset(
    h5f,
    src_name: str,
    dst_name: str,
    chunk_rows: int = 4096,
    flush_every: int = 8,
    dtype: Optional[np.dtype] = None,
) -> None:
    """dst[j, i] = src[i, j], streamed `chunk_rows` source rows at a time
    (reference shared_utils/util.py:591-615). Works for datasets far
    larger than RAM; column-slab writes land in dst's chunk cache."""
    src = h5f[src_name]
    n, m = src.shape
    dst = h5f.create_dataset(
        dst_name, shape=(m, n), dtype=dtype or src.dtype,
        chunks=(min(m, chunk_rows), min(n, chunk_rows)),
    )
    for k, lo in enumerate(range(0, n, chunk_rows)):
        hi = min(lo + chunk_rows, n)
        dst[:, lo:hi] = src[lo:hi, :].T
        if flush_every and (k + 1) % flush_every == 0:
            flush_h5_file(h5f)
    flush_h5_file(h5f)


def normalize(x: np.ndarray, axis: int = -1, eps: float = 1e-12) -> np.ndarray:
    """L2-normalize along `axis` (reference shared_utils/util.py:509-520)."""
    x = np.asarray(x, dtype=np.float64)
    return x / np.maximum(np.linalg.norm(x, axis=axis, keepdims=True), eps)


def random_mask(shape, p: float, rng: np.random.Generator) -> np.ndarray:
    """Bool mask, True w.p. p (reference shared_utils/util.py:523-535)."""
    return rng.random(shape) < p


def find_linearly_independent_columns(
    x: np.ndarray, tol: float = 1e-8
) -> list:
    """Indices of a maximal linearly-independent column subset via rank-
    revealing QR (reference's Gram-Schmidt loop at
    shared_utils/util.py:554-588, done with lapack instead)."""
    from scipy.linalg import qr

    x = np.asarray(x, dtype=np.float64)
    if x.size == 0:
        return []
    _, r, piv = qr(x, mode="economic", pivoting=True)
    diag = np.abs(np.diag(r)) if r.ndim == 2 else np.abs(r[:1])
    rank = int((diag > tol * (diag[0] if diag.size else 1.0)).sum())
    return sorted(piv[:rank].tolist())
