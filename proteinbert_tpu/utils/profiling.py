"""Wall-clock + device profiling (reference C20, TPU-aware).

The reference ships `TimeMeasure` (a with-block wall-clock logger,
shared_utils/util.py:1212-1223) and `Profiler` (named aggregating
time/invoke counters, shared_utils/util.py:1226-1263). Both are kept —
they are genuinely useful on the host side — and joined by
`device_trace()`, a thin wrapper over `jax.profiler` that captures an XLA
trace viewable in TensorBoard/Perfetto, which is the real profiling story
on TPU (per-op time lives on device, invisible to host timers).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from proteinbert_tpu.utils.logging import log


class TimeMeasure:
    """`with TimeMeasure('phase'):` — logs elapsed wall-clock on exit."""

    def __init__(self, name: str = "", verbose: bool = True):
        self.name = name
        self.verbose = verbose
        self.elapsed: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self.verbose:
            log(f"{self.name or 'block'}: {self.elapsed:.3f}s")
        return False


class Profiler:
    """Named aggregating profiler — now a thin shim over the telemetry
    metrics registry (obs/metrics.py), which absorbed the host-timer
    aggregation this class used to hold privately. The API is unchanged
    (`measure`/`summary`/`report`), and existing call sites keep
    working; pass a shared `registry` to fold a Profiler's sections
    into a run's unified metrics stream instead of a private one."""

    def __init__(self, registry=None):
        from proteinbert_tpu.obs.metrics import MetricsRegistry

        self._reg = registry if registry is not None else MetricsRegistry()

    def measure(self, name: str):
        return self._reg.timer(name)

    def summary(self) -> Dict[str, Dict[str, float]]:
        return self._reg.timer_summary()

    def report(self) -> str:
        rows = sorted(self.summary().items(),
                      key=lambda kv: -kv[1]["total_s"])
        return "\n".join(
            f"{name}: {s['total_s']:.3f}s / {s['count']} calls "
            f"({s['mean_s'] * 1e3:.2f} ms each)"
            for name, s in rows
        )


class BoundaryStallMeter:
    """Per-event host-stall meter: how long a dispatch loop spent inside
    a boundary region (checkpoint save, eval bracket) instead of
    enqueuing device work. This is the number the overlapped-boundary
    work optimizes — wall seconds the train stream stood still — and
    what `bench.py --boundary` reports for the synchronous vs staged
    checkpoint paths. Distinct from StepTimer.overlap: that accounts
    hidden seconds inside a live training run; this measures the stall
    itself, in isolation, for before/after comparison."""

    def __init__(self):
        self.stalls: list = []

    @contextlib.contextmanager
    def boundary(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stalls.append(time.perf_counter() - t0)

    def summary(self) -> Dict[str, float]:
        n = len(self.stalls)
        if not n:
            return {"boundaries": 0}
        return {
            "boundaries": n,
            "mean_s": sum(self.stalls) / n,
            # The comparison statistic for small samples: one GC pause
            # or scheduler hiccup inside a single boundary swings a
            # 4-sample mean by 2-3x; the median holds steady.
            "median_s": sorted(self.stalls)[n // 2],
            "max_s": max(self.stalls),
            "total_s": sum(self.stalls),
        }


@contextlib.contextmanager
def device_trace(log_dir: str, host_profile: bool = False):
    """Capture a jax.profiler trace (XLA ops, HBM, fusion view) to
    `log_dir`; open with TensorBoard or ui.perfetto.dev."""
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_trace=host_profile)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log(f"device trace written to {log_dir}")


def monitor_memory(threshold_bytes: int = 100 * 1024 ** 2,
                   collect: bool = False, verbose: bool = True):
    """Log every live host array buffer >= `threshold_bytes` (reference
    shared_utils/util.py:175-228's heap walker). Walks every gc-tracked
    container (module __dict__s included) plus the `__dict__` of every
    gc-tracked instance, and recurses through UNTRACKED containers found
    inside them — CPython untracks a dict/tuple whose members are all
    untracked (a tuple-of-arrays pytree, an instance __dict__ holding
    only arrays), so such nests are reachable only through a tracked
    ancestor. Returns [(type_name, nbytes), ...] largest first,
    deduplicated by identity; optionally gc.collect()s afterwards like
    the reference.
    """
    import collections
    import gc

    def size_of(obj):
        # Probe `nbytes` (numpy / jax buffers) through the TYPE, never the
        # instance: instance getattr would fire arbitrary __getattr__ on
        # every live object (observed force-registering pytest marks;
        # would force-initialize lazy proxies heap-wide). Everything is
        # guarded — even isinstance raises on a dead weakref.proxy.
        try:
            if isinstance(obj, (bytes, bytearray)):
                return len(obj)
            desc = getattr(type(obj), "nbytes", None)
            if desc is None or not hasattr(desc, "__get__"):
                return None
            n = desc.__get__(obj, type(obj))
        except Exception:  # dead weakproxies, raising descriptors
            return None
        return n if isinstance(n, int) else None

    seen: Dict[int, tuple] = {}
    visited: set = set()
    # Strong references to every object whose id() lands in `visited` or
    # `seen`: the stack pops its only reference to intermediate objects,
    # and if one were collected mid-walk CPython could reuse its id for a
    # genuinely new container/buffer — silently skipping it or
    # overwriting a seen entry (ADVICE r1). Pinning them for the walk's
    # duration makes id-dedup sound; the list is released on return.
    pinned: list = []
    # The walker's own bookkeeping is gc-tracked and MUTATES during the
    # walk — iterating it would raise "changed size during iteration".
    internals = {id(seen), id(visited), id(pinned)}

    # Iterative walk (an explicit stack): deep pathological nests must
    # not RecursionError a diagnostic tool. Only containers enter
    # `visited` — recording every leaf id would balloon the walker's own
    # footprint on multi-million-element lists (`seen` already dedups
    # leaf buffers by id).
    containers = (dict, list, tuple, set, frozenset, collections.deque)
    stack = []
    internals.add(id(stack))  # gc-listed below; must not walk itself
    for c in gc.get_objects():
        # Everything here is guarded: a dead weakref.proxy raises
        # ReferenceError from isinstance itself (it forwards __class__ to
        # the collected referent).
        try:
            if isinstance(c, containers):
                stack.append(c)
            else:
                # Instances are gc-tracked even when their __dict__ is
                # not (all-untracked values, e.g. only numpy arrays on
                # self) — the commonest big-buffer holder. Find the
                # __dict__ slot through the TYPE's mro: plain getattr
                # would fall through to instance __getattr__ on
                # __slots__ classes and fire lazy-proxy side effects
                # heap-wide (the same hazard size_of avoids).
                d = None
                for klass in type(c).__mro__:
                    desc = klass.__dict__.get("__dict__")
                    if desc is not None:
                        d = desc.__get__(c, type(c))
                        break
                if isinstance(d, dict):
                    stack.append(d)
        except Exception:
            continue
    while stack:
        obj = stack.pop()
        # issubclass(type(obj), ...) not isinstance: a dead weakref.proxy
        # forwards __class__ to its collected referent and raises from
        # isinstance, while type() never forwards.
        if issubclass(type(obj), containers):
            if id(obj) in visited or id(obj) in internals:
                continue
            visited.add(id(obj))
            pinned.append(obj)
            try:
                if isinstance(obj, dict):
                    # keys too: bytes keys are legal and can be large
                    stack.extend(list(obj.keys()))
                    stack.extend(list(obj.values()))
                else:
                    stack.extend(list(obj))
            except Exception:
                # Mutated mid-iteration by another thread (prefetch,
                # jax-internal), or a container subclass whose iteration
                # raises; skip it rather than crash a diagnostic.
                continue
        else:
            n = size_of(obj)
            if n is not None and n >= threshold_bytes:
                if id(obj) not in seen:
                    pinned.append(obj)
                seen[id(obj)] = (type(obj).__name__, n)

    found = sorted(seen.values(), key=lambda kv: -kv[1])
    if verbose:
        for name, n in found:
            log(f"monitor_memory: {name} {n / 1024 ** 2:.0f} MB")
        if not found:
            log(f"monitor_memory: no object >= "
                f"{threshold_bytes / 1024 ** 2:.0f} MB")
    if collect:
        gc.collect()
    return found


def device_memory_report() -> Dict[str, Dict[str, int]]:
    """Per-device HBM stats ({device: {bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...}}) — the on-chip counterpart of monitor_memory; the
    numbers XLA's allocator actually enforces (a 16 GB v5e OOMs on
    bytes_in_use, not on host heap size)."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats() or {}
        except Exception:  # backends without memory_stats (e.g. some CPU)
            stats = {}
        out[str(d)] = {k: int(v) for k, v in stats.items()
                       if isinstance(v, (int, float))}
    return out
