"""Wall-clock + device profiling (reference C20, TPU-aware).

The reference ships `TimeMeasure` (a with-block wall-clock logger,
shared_utils/util.py:1212-1223) and `Profiler` (named aggregating
time/invoke counters, shared_utils/util.py:1226-1263). Both are kept —
they are genuinely useful on the host side — and joined by
`device_trace()`, a thin wrapper over `jax.profiler` that captures an XLA
trace viewable in TensorBoard/Perfetto, which is the real profiling story
on TPU (per-op time lives on device, invisible to host timers).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

from proteinbert_tpu.utils.logging import log


class TimeMeasure:
    """`with TimeMeasure('phase'):` — logs elapsed wall-clock on exit."""

    def __init__(self, name: str = "", verbose: bool = True):
        self.name = name
        self.verbose = verbose
        self.elapsed: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self.verbose:
            log(f"{self.name or 'block'}: {self.elapsed:.3f}s")
        return False


class Profiler:
    """Named aggregating profiler: total time + invoke count per name."""

    def __init__(self):
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def measure(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._totals[name] = self._totals.get(name, 0.0) + dt
            self._counts[name] = self._counts.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": self._totals[name],
                "count": self._counts[name],
                "mean_s": self._totals[name] / self._counts[name],
            }
            for name in self._totals
        }

    def report(self) -> str:
        rows = sorted(self._totals.items(), key=lambda kv: -kv[1])
        return "\n".join(
            f"{name}: {total:.3f}s / {self._counts[name]} calls "
            f"({total / self._counts[name] * 1e3:.2f} ms each)"
            for name, total in rows
        )


@contextlib.contextmanager
def device_trace(log_dir: str, host_profile: bool = False):
    """Capture a jax.profiler trace (XLA ops, HBM, fusion view) to
    `log_dir`; open with TensorBoard or ui.perfetto.dev."""
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_trace=host_profile)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log(f"device trace written to {log_dir}")
