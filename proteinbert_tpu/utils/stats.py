"""Statistics helpers (reference C22 parity, the parts worth keeping).

The reference vendors a large stats/plot grab-bag (reference
shared_utils/util.py:697-1105). The numeric pieces are reimplemented here
with scipy/numpy; plotting wrappers are provided behind a lazy matplotlib
import (matplotlib is optional in this image). The reference's
`as_hot_encoding` forgets its return statement (reference
shared_utils/util.py:538-551, SURVEY ledger #12) — `one_hot` here
actually returns.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np


def one_hot(labels: Sequence, num_classes: Optional[int] = None) -> np.ndarray:
    """(N, num_classes) 0/1 matrix (fixes reference ledger #12)."""
    labels = np.asarray(labels, dtype=np.int64)
    if labels.size and labels.min() < 0:
        raise ValueError("labels must be non-negative")
    k = num_classes if num_classes is not None else (int(labels.max()) + 1
                                                    if labels.size else 0)
    if labels.size and labels.max() >= k:
        raise ValueError(
            f"label {int(labels.max())} out of range for {k} classes")
    out = np.zeros((len(labels), k), dtype=np.float32)
    if labels.size:
        out[np.arange(len(labels)), labels] = 1.0
    return out


def drop_redundant_columns(x: np.ndarray, tol: float = 1e-8) -> np.ndarray:
    """Keep a maximal linearly-independent column subset — the dummy-
    variable-trap / quasi-separation guard of the reference's regression
    helpers (reference shared_utils/util.py:697-872), reduced to its
    numeric core."""
    from proteinbert_tpu.utils.h5 import find_linearly_independent_columns

    return np.asarray(x)[:, find_linearly_independent_columns(x, tol)]


def benjamini_hochberg(pvals: Sequence[float]) -> np.ndarray:
    """FDR-adjusted q-values (reference shared_utils/util.py:888-898)."""
    p = np.asarray(pvals, dtype=np.float64)
    n = p.size
    if n == 0:
        return p
    order = np.argsort(p)
    ranked = p[order] * n / np.arange(1, n + 1)
    # enforce monotonicity from the largest rank down
    ranked = np.minimum.accumulate(ranked[::-1])[::-1]
    out = np.empty(n)
    out[order] = np.minimum(ranked, 1.0)
    return out


def benjamini_hochberg_with_nulls(
    pvals: Sequence[float], alpha: float = 0.05,
) -> Tuple[np.ndarray, np.ndarray]:
    """NaN-tolerant FDR adjustment (reference
    shared_utils/util.py:888-898, `multipletests_with_nulls`).

    Entries that are NaN (e.g. tests that could not be run) are excluded
    from the BH ranking — so they neither consume rank slots nor dilute
    the correction for the real p-values — and come back as
    (significance=False, qval=NaN). Returns ``(significance, qvals)``
    where ``significance = qvals <= alpha`` on the non-null subset,
    matching statsmodels' ``multipletests(..., method='fdr_bh')``
    convention the reference delegates to."""
    p = np.asarray(pvals, dtype=np.float64)
    significance = np.zeros(p.shape, dtype=bool)
    qvals = np.full(p.shape, np.nan)
    mask = ~np.isnan(p)
    if mask.any():
        q = benjamini_hochberg(p[mask])
        qvals[mask] = q
        significance[mask] = q <= alpha
    return significance, qvals


def fisher_enrichment(
    n_overlap: int, n_set1: int, n_set2: int, n_total: int,
) -> Tuple[float, float]:
    """(odds_ratio, p_value) of the overlap of two sets under a universe
    of n_total, one-sided greater — the reference's enrichment test
    (reference shared_utils/util.py:901-937)."""
    from scipy.stats import fisher_exact

    a = n_overlap
    b = n_set1 - n_overlap
    c = n_set2 - n_overlap
    d = n_total - n_set1 - n_set2 + n_overlap
    if min(a, b, c, d) < 0:
        raise ValueError(
            f"inconsistent counts: overlap={n_overlap} set1={n_set1} "
            f"set2={n_set2} total={n_total}")
    odds, p = fisher_exact([[a, b], [c, d]], alternative="greater")
    return float(odds), float(p)


def _plt():
    try:
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover - matplotlib is optional
        raise ImportError(
            "plot helpers need matplotlib, which is optional in this "
            "environment") from e


def qq_plot(pvals: Sequence[float], out_path: str) -> None:
    """Observed vs expected -log10(p) (reference
    shared_utils/util.py:968-1020), written to `out_path`."""
    plt = _plt()
    p = np.sort(np.asarray(pvals, dtype=np.float64))
    p = np.clip(p, 1e-300, 1.0)
    n = p.size
    if n == 0:
        raise ValueError("qq_plot needs at least one p-value")
    exp = -np.log10((np.arange(1, n + 1) - 0.5) / n)
    obs = -np.log10(p)
    fig, ax = plt.subplots(figsize=(4, 4))
    ax.plot(exp, obs, ".", ms=3)
    lim = max(exp.max(), obs.max()) * 1.05
    ax.plot([0, lim], [0, lim], "r--", lw=1)
    ax.set_xlabel("expected -log10(p)")
    ax.set_ylabel("observed -log10(p)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def scatter_plot(x, y, out_path: str, xlabel: str = "", ylabel: str = "") -> None:
    """Basic labeled scatter (reference shared_utils/util.py:1023-1105)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(4, 4))
    ax.plot(np.asarray(x), np.asarray(y), ".", ms=3)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def manhattan_plot(
    chrom_labels: Sequence, positions: Sequence[int],
    pvals: Sequence[float], out_path: str,
) -> None:
    """-log10(p) by genomic position, chromosomes concatenated on the x
    axis in alternating shades (reference shared_utils/util.py:968-1105's
    Manhattan variant). `chrom_labels` groups the points; groups are laid
    out in first-appearance order."""
    plt = _plt()
    chrom_labels = list(chrom_labels)
    positions = np.asarray(positions, dtype=np.float64)
    logs = -np.log10(np.clip(np.asarray(pvals, np.float64), 1e-300, 1.0))
    if not (len(chrom_labels) == positions.size == logs.size):
        raise ValueError("chrom_labels, positions, pvals must align")

    # Group by label via dict lookup (one O(n) pass, insertion-ordered).
    # Deliberately NOT numpy `==`: a NaN label from a pandas column would
    # match nothing under eq (nan != nan) and crash on an empty group,
    # while dict hashing groups identical objects fine.
    groups: dict = {}
    for i, c in enumerate(chrom_labels):
        groups.setdefault(c, []).append(i)
    fig, ax = plt.subplots(figsize=(8, 3))
    offset = 0.0
    ticks, tick_labels = [], []
    for g, (c, idx_list) in enumerate(groups.items()):
        idx = np.asarray(idx_list)
        pos = positions[idx]
        span = pos.max() - pos.min() + 1
        ax.plot(pos - pos.min() + offset, logs[idx], ".", ms=2,
                color=("tab:blue", "tab:gray")[g % 2])
        ticks.append(offset + span / 2)
        tick_labels.append(str(c))
        offset += span
    ax.set_xticks(ticks, tick_labels, rotation=90, fontsize=6)
    ax.set_ylabel("-log10(p)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def write_excel(sheets: dict, out_path: str, fallback_csv: bool = True) -> list:
    """Write {sheet_name: DataFrame} to one .xlsx (reference
    shared_utils/util.py:794-805). An xlsx engine (openpyxl/xlsxwriter) is
    optional in this image; with `fallback_csv` the sheets are written as
    `<out_path>.<sheet>.csv` instead when no engine exists. Returns the
    list of paths written."""
    import pandas as pd

    try:
        with pd.ExcelWriter(out_path) as writer:
            for name, df in sheets.items():
                pd.DataFrame(df).to_excel(writer, sheet_name=str(name))
        return [out_path]
    except ImportError:
        if not fallback_csv:
            raise ImportError(
                "write_excel needs openpyxl or xlsxwriter (optional in "
                "this environment); pass fallback_csv=True for CSVs")
        paths = []
        for name, df in sheets.items():
            p = f"{out_path}.{name}.csv"
            pd.DataFrame(df).to_csv(p)
            paths.append(p)
        return paths


@functools.lru_cache(maxsize=4)
def _build_chain_index(chain_file: str):
    from pyliftover import LiftOver

    return LiftOver(chain_file)


def _chain_index(chain_file: str):
    """Cached pyliftover.LiftOver per chain file — construction parses
    and indexes the whole UCSC chain (seconds), and the natural caller
    loops liftover_positions per chromosome over the same chain."""
    try:
        return _build_chain_index(chain_file)
    except ImportError as e:
        raise ImportError(
            "liftover_positions needs pyliftover, which is optional in "
            "this environment") from e


def liftover_positions(
    chain_file: str, chrom: str, positions: Sequence[int],
    one_based: bool = False,
) -> list:
    """Map genomic coordinates across assemblies via a UCSC chain file
    (reference shared_utils/util.py:1161-1200). Positions are 0-based
    (pyliftover's convention) unless `one_based=True`, in which case both
    inputs and outputs use the 1-based VCF/GWAS convention. Returns
    [(chrom, pos) | None, ...] per input position. pyliftover is optional
    in this image — absent, this raises with a clear message (the
    reference lazily imports it the same way)."""
    lo = _chain_index(chain_file)
    shift = 1 if one_based else 0
    out = []
    for pos in positions:
        hits = lo.convert_coordinate(chrom, int(pos) - shift)
        out.append((hits[0][0], int(hits[0][1]) + shift) if hits else None)
    return out
