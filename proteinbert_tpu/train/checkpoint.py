"""Orbax-based sharded async checkpointing (reference utils.py:324-343, redone).

The reference `torch.save`s a dict of state_dicts every 1000 iterations
and a final pickled nn.Module (reference utils.py:326-343), losing RNG
state and — because of the head-registration bug — the attention weights
(SURVEY §5). Here the WHOLE TrainState pytree (params, opt_state, PRNG
key, step) plus the data-iterator position is saved through orbax:
sharded (each host writes its own shards), optionally async (save
overlaps the next train steps), with automatic retention of the last
`max_to_keep` checkpoints.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    """Thin CheckpointManager wrapper bound to one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
            # Registering the per-item handlers up front lets
            # item_metadata() (used by restore to detect the optional
            # 'data' item) resolve without orbax's "could not be
            # restored" warning on every CLI restore.
            item_handlers={
                "state": ocp.StandardCheckpointHandler(),
                "data": ocp.JsonCheckpointHandler(),
            },
        )

    def save(self, step: int, state: Any, data_state: Optional[Dict] = None) -> bool:
        """Returns orbax's outcome: False means the manager SILENTLY
        skipped (it does so for any step <= latest_step, not only
        exact duplicates) — callers that need the save to have
        happened (warm start, preemption) must check, not assume."""
        args = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            args["data"] = ocp.args.JsonSave(data_state)
        return bool(self._mngr.save(step, args=ocp.args.Composite(**args)))

    def all_steps(self):
        return list(self._mngr.all_steps())

    def restore(self, state_like: Any, step: Optional[int] = None):
        """Restore (state, data_state) at `step` (default: latest).

        `state_like` is a concrete or abstract TrainState pytree used as
        the restore target — its shardings tell orbax where each shard
        goes (single-host or multi-host).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            return None, None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        args = {"state": ocp.args.StandardRestore(abstract)}
        # 'data' is optional at save time; requesting an absent item raises.
        if "data" in (self._mngr.item_metadata(step) or {}):
            args["data"] = ocp.args.JsonRestore()
        restored = self._mngr.restore(step, args=ocp.args.Composite(**args))
        return restored["state"], restored.get("data")

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def in_flight(self) -> bool:
        """True while an async save is still writing. The trainer ORs
        this with a started-since-last-log latch and stamps the result
        into each logged metrics record (`ckpt_in_flight`) so a slow
        window in the stream can be attributed to (or cleared of)
        checkpoint I/O contending for host/tunnel bandwidth — the
        leading suspect for the r3 sustained run's collapse. (The latch
        matters: a point sample alone would miss a save that started
        and finished between two log points.)"""
        return bool(self._mngr.is_saving_in_progress())

    def wait(self) -> None:
        """Block until pending async saves land (call before process exit)."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
