"""Orbax-based sharded async checkpointing (reference utils.py:324-343, redone).

The reference `torch.save`s a dict of state_dicts every 1000 iterations
and a final pickled nn.Module (reference utils.py:326-343), losing RNG
state and — because of the head-registration bug — the attention weights
(SURVEY §5). Here the WHOLE TrainState pytree (params, opt_state, PRNG
key, step) plus the data-iterator position is saved through orbax:
sharded (each host writes its own shards), optionally async (save
overlaps the next train steps), with automatic retention of the last
`max_to_keep` checkpoints.

On top of orbax's async write, `save_staged` overlaps the part orbax
keeps synchronous — the device→host state fetch: the trainer snapshots
the state on device (train_state.snapshot_train_state), hands the copy
here, and a stager thread fetches + saves it while the train stream
keeps dispatching. One stage in flight (backpressure via flush);
worker errors re-raise at the next flush/poll/wait; orbax's silent
skip-at-old-step stays loudly surfaced.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class Checkpointer:
    """Thin CheckpointManager wrapper bound to one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        # Optional telemetry hook: callable(phase, step, **info), phase
        # in obs.events.CKPT_PHASES ("dispatch" at save_staged,
        # "landed" when a stage joins with its overlap_s, "save" for a
        # direct synchronous save). The trainer points this at
        # Telemetry.emit("ckpt_stage", ...); errors in the hook are
        # logged, never allowed to fail a save.
        self.on_event = None
        # Optional restore-side hook: callable(**fields), pointed at
        # Telemetry.emit("note", ...) — reports a torn-final-checkpoint
        # fallback (restore() docstring); errors logged, never raised.
        self.on_note = None
        # Staged (overlapped) save slot: at most ONE in flight — the
        # double-buffer is {the device-side snapshot} + {the host copy
        # the stager fetches into}; a second boundary arriving while a
        # stage is in flight back-pressures through flush_staged().
        self._staged: Optional[tuple] = None  # (future, holder dict)
        # ONE dedicated saver thread for every manager.save call, staged
        # or direct: orbax's CheckpointManager requires all saves to
        # originate from the SAME thread — its wait-for-previous-
        # finalize bookkeeping only resets `_finalize_thread` when the
        # waiter IS the thread that requested the previous save, so a
        # save from any other thread trips `assert _finalize_thread is
        # None` whenever an async finalize is still alive.
        self._saver = ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="ckpt-saver")
        self._saver_thread: Optional[threading.Thread] = None
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
            # Registering the per-item handlers up front lets
            # item_metadata() (used by restore to detect the optional
            # 'data' item) resolve without orbax's "could not be
            # restored" warning on every CLI restore.
            item_handlers={
                "state": ocp.StandardCheckpointHandler(),
                "data": ocp.JsonCheckpointHandler(),
            },
        )

    def _on_saver(self, fn):
        """Run `fn` on the dedicated saver thread (directly when already
        on it — the staged work function calls save() from there) and
        return its result; exceptions propagate to the caller."""
        if threading.current_thread() is self._saver_thread:
            return fn()

        def run():
            self._saver_thread = threading.current_thread()
            return fn()

        return self._saver.submit(run).result()

    def _notify(self, phase: str, step: int, **info) -> None:
        cb = self.on_event
        if cb is None:
            return
        try:
            cb(phase, step, **info)
        except Exception:
            logger.exception("checkpoint on_event hook failed (phase=%s "
                             "step=%d) — save path unaffected", phase, step)

    def save(self, step: int, state: Any, data_state: Optional[Dict] = None,
             _from_stage: bool = False) -> bool:
        """Returns orbax's outcome: False means the manager SILENTLY
        skipped (it does so for any step <= latest_step, not only
        exact duplicates) — callers that need the save to have
        happened (warm start, preemption) must check, not assume.
        Blocks the caller for the synchronous part of the save (the
        write itself is async when async_save); the manager call runs
        on the saver thread (see __init__)."""
        args = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            args["data"] = ocp.args.JsonSave(data_state)
        composite = ocp.args.Composite(**args)
        saved = bool(self._on_saver(
            lambda: self._mngr.save(step, args=composite)))
        if not _from_stage:
            # Staged saves report through "dispatch"/"landed" instead
            # (this synchronous-save event from the stager worker would
            # double-count the boundary).
            self._notify("save", step, saved=saved)
        return saved

    # ---------------------------------------------- overlapped (staged) saves

    def _stage_fetch(self, snapshot: Any) -> Any:
        """Device→host fetch of an (already device-copied) snapshot; runs
        on the stager thread. A method so tests can interpose latency."""
        return jax.device_get(snapshot)

    def save_staged(self, step: int, snapshot: Any,
                    data_state: Optional[Dict] = None) -> None:
        """Hand a DEVICE-SIDE snapshot (train_state.snapshot_train_state)
        to a background fetch+save and return immediately — the caller's
        train stream keeps dispatching while the device→host transfer
        and the orbax write run on the stager thread (the transfer has
        no data dependency on later train steps, so it costs ~zero wall
        time instead of the 19–47 s stop-the-world of a synchronous
        boundary).

        Backpressure rule: one stage in flight. If a previous stage has
        not landed when the next boundary arrives, this call BLOCKS in
        flush_staged() first — that wait is real stall and the trainer
        deliberately leaves it inside the timed window.

        Error/skip semantics: a stager exception is re-raised at the
        next flush_staged()/poll_staged()/wait() (never swallowed); an
        orbax silent skip (step <= latest) is surfaced with the same
        loud warning the synchronous path logs."""
        self.flush_staged()
        holder: Dict[str, Any] = {"step": step}

        def work():
            self._saver_thread = threading.current_thread()
            t0 = time.perf_counter()
            try:
                host_state = self._stage_fetch(snapshot)
                holder["saved"] = self.save(step, host_state, data_state,
                                            _from_stage=True)
            finally:
                holder["overlap_s"] = time.perf_counter() - t0

        self._notify("dispatch", step)
        self._staged = (self._saver.submit(work), holder)

    def flush_staged(self) -> Optional[Dict[str, Any]]:
        """Join the in-flight staged save (no-op when none). Re-raises a
        stager exception; logs the loud SKIPPED warning when orbax
        silently refused the step. Returns the stage's stats
        ({step, saved, overlap_s}) or None."""
        if self._staged is None:
            return None
        fut, holder = self._staged
        self._staged = None
        fut.result()  # joins; re-raises a stager exception
        if not holder.get("saved"):
            logger.warning(
                "staged checkpoint save at step %d was SKIPPED by the "
                "manager (directory already holds a step >= %d) — state "
                "was NOT written", holder["step"], holder["step"])
        self._notify("landed", holder["step"],
                     saved=bool(holder.get("saved")),
                     overlap_s=round(holder.get("overlap_s", 0.0), 6))
        return holder

    def poll_staged(self) -> Optional[Dict[str, Any]]:
        """Non-blocking flush: stats if the in-flight stage has finished
        (errors/skips surfaced exactly as flush_staged), else None."""
        if self._staged is None or not self._staged[0].done():
            return None
        return self.flush_staged()

    def staged_in_flight(self) -> bool:
        return self._staged is not None and not self._staged[0].done()

    def all_steps(self):
        return list(self._mngr.all_steps())

    def restore(self, state_like: Any, step: Optional[int] = None,
                fallback: bool = True):
        """Restore (state, data_state) at `step` (default: latest).

        `state_like` is a concrete or abstract TrainState pytree used as
        the restore target — its shardings tell orbax where each shard
        goes (single-host, multi-host, or an entirely DIFFERENT mesh
        layout than the one that wrote the checkpoint: orbax reshards
        from disk against the template's shardings, which is the restore
        half of mesh-agnostic resharding, parallel/reshard.py).

        Torn-tail tolerance (`fallback=True`, default, applies only when
        `step` is None): when the NEWEST checkpoint is torn or missing —
        a crash mid-write of the final step, the read-side mirror of the
        write-side torn-snapshot guarantees — restore falls back to the
        previous retained step instead of raising, reporting the skip
        through `on_note` (wired to a `note` telemetry event by the
        trainer/CLI). Exactly ONE step is ever skipped: a crash can
        tear at most the in-flight write, so a failure at the fallback
        step too is a REAL error (wrong restore template, corrupted
        store) and raises as itself instead of being smeared into more
        "torn checkpoint" notes. An explicitly requested `step` stays
        strict, and a single-step directory re-raises the original
        error.
        """
        explicit = step is not None
        steps = ([step] if explicit
                 else sorted(self.all_steps(), reverse=True))
        if not steps:
            return None, None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        # Donation-safety canonicalization — never return orbax's
        # arrays directly (copy_pytree's docstring has the jax-0.4.37
        # warm-cache segfault repro this guards against).
        from proteinbert_tpu.train.train_state import copy_pytree

        for i, s in enumerate(steps):
            try:
                args = {"state": ocp.args.StandardRestore(abstract)}
                # 'data' is optional at save time; requesting an absent
                # item raises.
                if "data" in (self._mngr.item_metadata(s) or {}):
                    args["data"] = ocp.args.JsonRestore()
                restored = self._mngr.restore(
                    s, args=ocp.args.Composite(**args))
                return copy_pytree(restored["state"]), restored.get("data")
            except (FileNotFoundError, ValueError, KeyError,
                    TypeError) as exc:
                # The types orbax surfaces a torn step dir as, depending
                # on which file is missing — and ONLY those: a transient
                # failure restoring an intact step (device OOM, a flaky
                # filesystem read) must raise, not silently roll the run
                # back a checkpoint interval.
                if explicit or not fallback or i > 0 or len(steps) == 1:
                    raise
                logger.warning(
                    "checkpoint at step %d in %s is unreadable (%s: %s) "
                    "— falling back to the previous retained step %d",
                    s, self.directory, type(exc).__name__, exc,
                    steps[i + 1])
                self._note_restore_fallback(s, steps[i + 1], exc)
        raise AssertionError("unreachable: the loop returns or raises")

    def _note_restore_fallback(self, bad_step: int, landed_step: int,
                               exc: Exception) -> None:
        """Report one skipped-torn-step event through `on_note`
        (callable(**fields) — the trainer/CLI points it at
        Telemetry.emit('note', ...)); never allowed to fail a restore.
        The payload carries BOTH the skipped step (`bad_step`) and the
        step the restore falls back to (`landed_step`) so an operator
        reading the stream knows exactly how much history the run lost
        without cross-referencing the directory listing."""
        cb = getattr(self, "on_note", None)
        if cb is None:
            return
        try:
            cb(source="checkpoint", kind="restore_fallback",
               bad_step=int(bad_step), landed_step=int(landed_step),
               error=f"{type(exc).__name__}: {exc}")
        except Exception:
            logger.exception("checkpoint on_note hook failed — restore "
                             "path unaffected")

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def in_flight(self) -> bool:
        """True while an async OR staged save is still writing. The
        trainer ORs this with a started-since-last-log latch and stamps
        the result into each logged metrics record (`ckpt_in_flight`) so
        a slow window in the stream can be attributed to (or cleared of)
        checkpoint I/O contending for host/tunnel bandwidth — the
        leading suspect for the r3 sustained run's collapse. Under the
        overlapped boundary this latch marks a REAL overlap window (the
        staged fetch+write running behind training), not contention.
        (The latch matters: a point sample alone would miss a save that
        started and finished between two log points.)"""
        return bool(self.staged_in_flight()
                    or self._mngr.is_saving_in_progress())

    def wait(self) -> None:
        """Block until pending staged AND async saves land (call before
        process exit); staged-worker errors propagate from here."""
        self.flush_staged()
        self._mngr.wait_until_finished()

    def close(self) -> None:
        try:
            self.flush_staged()
        finally:
            self._saver.shutdown(wait=True)
            self._mngr.close()
