"""Dual masked pretraining loss (reference utils.py:293-295, from logits).

The reference computes `mean(CE(local)·w) + mean(BCE(global)·w)` with a
double-softmax bug (probability-emitting heads into CrossEntropyLoss,
reference modules.py:277-293 + utils.py:293, SURVEY ledger #3). Here both
terms are computed from LOGITS via optax, and each term is a weighted mean
normalized by the weight mass (sum(w·loss)/sum(w)) rather than the
reference's mean-over-all-elements — so the loss scale is invariant to
padding fraction and annotation sparsity (documented divergence).

Weights follow the reference contract (reference data_processing.py:
175-176): local w = non-pad mask of the clean sequence; global w = 1 iff
the protein has any positive annotation.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax


def _weighted_mean(loss: jax.Array, w: jax.Array) -> jax.Array:
    return (loss * w).sum() / jnp.maximum(w.sum(), 1.0)


def pretrain_loss(
    local_logits: jax.Array,
    global_logits: jax.Array,
    targets: Dict[str, jax.Array],
    weights: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Total loss + per-term metrics.

    Args:
      local_logits: (B, L, V) fp32.
      global_logits: (B, A) fp32.
      targets: {"local": (B, L) int ids, "global": (B, A) 0/1}.
      weights: {"local": (B, L), "global": (B, A)} fp32 masks.
    """
    local_ce = optax.softmax_cross_entropy_with_integer_labels(
        local_logits, targets["local"]
    )
    local_loss = _weighted_mean(local_ce, weights["local"])

    global_bce = optax.sigmoid_binary_cross_entropy(
        global_logits, targets["global"]
    )
    global_loss = _weighted_mean(global_bce, weights["global"])

    total = local_loss + global_loss

    local_pred = local_logits.argmax(-1)
    local_acc = _weighted_mean(
        (local_pred == targets["local"]).astype(jnp.float32), weights["local"]
    )
    metrics = {
        "loss": total,
        "local_loss": local_loss,
        "global_loss": global_loss,
        "local_acc": local_acc,
    }
    return total, metrics


def packed_segment_losses(
    local_logits: jax.Array,
    global_logits: jax.Array,
    targets: Dict[str, jax.Array],
    weights: Dict[str, jax.Array],
    segment_ids: jax.Array,
) -> Dict[str, jax.Array]:
    """Per-SEGMENT loss terms for a packed batch (data/packing.py).

    Returns (B, S) arrays: "local" (mean token CE over the segment's
    positions), "global" (mean annotation BCE over the segment's
    weighted annotation dims), "local_acc", plus validity masks
    "seg_valid" (segment has positions) and "seg_weighted" (segment has
    global loss weight). These are exactly the quantities an UNPACKED
    run computes per row, which is what the packed-vs-unpacked parity
    test asserts (tests/test_packing.py).
    """
    S = global_logits.shape[1]
    onehot = (
        segment_ids[..., None] == jnp.arange(1, S + 1,
                                             dtype=segment_ids.dtype)
    ).astype(jnp.float32)  # (B, L, S)
    tok_w = weights["local"]  # (B, L)

    ce = optax.softmax_cross_entropy_with_integer_labels(
        local_logits, targets["local"]
    )  # (B, L)
    seg_tokens = jnp.einsum("bl,bls->bs", tok_w, onehot)
    seg_ce = jnp.einsum("bl,bls->bs", ce * tok_w, onehot)
    denom = jnp.maximum(seg_tokens, 1.0)
    per_seg_local = seg_ce / denom

    correct = (local_logits.argmax(-1) == targets["local"]).astype(
        jnp.float32)
    per_seg_acc = jnp.einsum("bl,bls->bs", correct * tok_w, onehot) / denom

    bce = optax.sigmoid_binary_cross_entropy(
        global_logits, targets["global"]
    )  # (B, S, A)
    gw = weights["global"]  # (B, S, A)
    gw_sum = gw.sum(axis=-1)
    per_seg_global = (bce * gw).sum(axis=-1) / jnp.maximum(gw_sum, 1.0)

    return {
        "local": per_seg_local,
        "global": per_seg_global,
        "local_acc": per_seg_acc,
        "seg_valid": (seg_tokens > 0).astype(jnp.float32),
        "seg_weighted": (gw_sum > 0).astype(jnp.float32),
    }


def packed_pretrain_loss(
    local_logits: jax.Array,
    global_logits: jax.Array,
    targets: Dict[str, jax.Array],
    weights: Dict[str, jax.Array],
    segment_ids: jax.Array,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """`pretrain_loss` for PACKED batches, normalized PER SEGMENT.

    Each term first averages within a segment, then averages over valid
    segments — so a 900-residue protein and a 40-residue one packed
    into the same row contribute equally, exactly as they would as two
    unpacked rows under a per-row normalization (documented divergence
    from the unpacked loss, which is token-weighted across the batch;
    the per-term SCALE matches the unpacked loss on any single
    sequence, which is the invariant transfer/eval comparisons need).

    Args:
      local_logits: (B, L, V) fp32.
      global_logits: (B, S, A) fp32.
      targets: {"local": (B, L) int ids, "global": (B, S, A) 0/1}.
      weights: {"local": (B, L), "global": (B, S, A)} fp32 masks
        (data/corruption.packed_weights).
      segment_ids: (B, L) int, 0 = pad.
    """
    seg = packed_segment_losses(
        local_logits, global_logits, targets, weights, segment_ids)
    local_loss = _weighted_mean(seg["local"], seg["seg_valid"])
    global_loss = _weighted_mean(seg["global"], seg["seg_weighted"])
    local_acc = _weighted_mean(seg["local_acc"], seg["seg_valid"])
    total = local_loss + global_loss
    metrics = {
        "loss": total,
        "local_loss": local_loss,
        "global_loss": global_loss,
        "local_acc": local_acc,
    }
    return total, metrics


def global_ranking_metrics(
    global_logits: jax.Array,
    targets: jax.Array,
    weights: jax.Array,
    k: int = 10,
) -> Dict[str, jax.Array]:
    """Ranking quality of the GO-annotation head — eval-only (the train
    step stays lean; eval_step adds these, trainer prefixes eval_).

    Returns:
      global_auroc: micro-averaged AUROC over all (protein, annotation)
        elements with weight > 0, computed rank-based (Mann-Whitney U)
        with ordinal tie-breaking — exact for the continuous logits the
        head emits. Elements with weight 0 (proteins with no positive
        annotation, reference data_processing.py:175-176 contract) are
        excluded from both the positive and negative pools.
      global_p_at_k: precision@k — fraction of each weighted protein's
        top-k scored annotations that are true, averaged over proteins.
    """
    valid = weights > 0
    labels = (targets > 0) & valid

    # --- micro AUROC. Invalid elements are pinned to -inf so they sit
    # below every valid score; their uniform contribution to positives'
    # ranks is subtracted in closed form.
    # All rank/count arithmetic in float32: at real shapes (B=256 x
    # A=8943) n_pos*n_neg ~ 4e9 overflows int32, and jax defaults to
    # 32-bit ints. float32's 24-bit mantissa leaves the metric exact to
    # ~1e-6 relative at these magnitudes, which is plenty for a metric.
    scores = jnp.where(valid, global_logits, -jnp.inf).reshape(-1)
    pos = labels.reshape(-1)
    val = valid.reshape(-1)
    order = jnp.argsort(scores)
    ranks = jnp.zeros((order.shape[0],), jnp.float32).at[order].set(
        jnp.arange(order.shape[0], dtype=jnp.float32))
    n_pos = pos.sum(dtype=jnp.float32)
    n_val = val.sum(dtype=jnp.float32)
    n_inv = order.shape[0] - n_val
    n_neg = n_val - n_pos
    rank_sum = jnp.where(pos, ranks, 0.0).sum()
    u = rank_sum - n_pos * (n_pos - 1) / 2 - n_pos * n_inv
    denom = jnp.maximum(n_pos * n_neg, 1.0)
    auroc = jnp.where((n_pos > 0) & (n_neg > 0), u / denom, 0.5)

    # --- precision@k per weighted protein. When NO row is weighted the
    # batch has zero positive annotations anywhere, so precision@k of any
    # ranking truly is 0 — unlike AUROC (a ratio of pairs) there is no
    # undefined case needing a neutral sentinel.
    k = min(k, global_logits.shape[-1])
    _, top_idx = jax.lax.top_k(global_logits, k)
    hits = jnp.take_along_axis(labels, top_idx, axis=-1)
    row_valid = valid.any(-1)
    p_at_k = _weighted_mean(
        hits.mean(-1).astype(jnp.float32), row_valid.astype(jnp.float32))

    return {"global_auroc": auroc.astype(jnp.float32),
            "global_p_at_k": p_at_k}


# Pooled (split-level) ranking metrics. A dataset-level micro-AUROC is not
# an average of per-batch AUROCs (VERDICT r2 Weak #5) — it needs the joint
# score distribution. These two functions split the computation into a
# per-batch, on-device sufficient statistic (mergeable by addition) and a
# tiny host-side finish, so an eval loop can pool exactly one
# (4*num_bins+8)-byte transfer per batch instead of all logits.

RANKING_BIN_LO = -30.0  # logit-space histogram range; ties only within a
RANKING_BIN_HI = 30.0   # (HI-LO)/num_bins ≈ 0.007-logit-wide bin
DEFAULT_RANKING_BINS = 8192


def global_ranking_stats(
    global_logits: jax.Array,
    targets: jax.Array,
    weights: jax.Array,
    k: int = 10,
    num_bins: int = DEFAULT_RANKING_BINS,
) -> Dict[str, jax.Array]:
    """Mergeable sufficient statistics for POOLED ranking metrics.

    Returns {"pos_hist", "neg_hist" (num_bins,), "p_at_k_num",
    "p_at_k_den" ()}; stats from different batches merge by elementwise
    addition, and `ranking_metrics_from_stats` finishes them into
    split-level micro-AUROC / precision@k. Scores are binned LINEARLY in
    logit space over [RANKING_BIN_LO, RANKING_BIN_HI] — monotone, and
    (unlike sigmoid binning) it does not collapse the very negative
    logits a sparse 8943-dim GO head mostly emits into one tied bin.
    Elements sharing a bin score as ties (half credit), so the pooled
    AUROC is exact up to the ~0.007-logit bin width.
    """
    valid = weights > 0
    labels = (targets > 0) & valid

    span = RANKING_BIN_HI - RANKING_BIN_LO
    pos_f = (global_logits - RANKING_BIN_LO) * (num_bins / span)
    bins = jnp.clip(pos_f.astype(jnp.int32), 0, num_bins - 1).reshape(-1)
    posf = labels.reshape(-1).astype(jnp.float32)
    negf = (valid.reshape(-1) & ~labels.reshape(-1)).astype(jnp.float32)
    pos_hist = jnp.zeros((num_bins,), jnp.float32).at[bins].add(posf)
    neg_hist = jnp.zeros((num_bins,), jnp.float32).at[bins].add(negf)

    k = min(k, global_logits.shape[-1])
    _, top_idx = jax.lax.top_k(global_logits, k)
    hits = jnp.take_along_axis(labels, top_idx, axis=-1)
    row_valid = valid.any(-1).astype(jnp.float32)
    return {
        "pos_hist": pos_hist,
        "neg_hist": neg_hist,
        "p_at_k_num": (hits.mean(-1).astype(jnp.float32) * row_valid).sum(),
        "p_at_k_den": row_valid.sum(),
    }


def ranking_metrics_from_stats(stats: Dict[str, Any]) -> Dict[str, float]:
    """Finish merged `global_ranking_stats` into split-level metrics
    (host-side, float64)."""
    import numpy as np

    pos = np.asarray(stats["pos_hist"], np.float64)
    neg = np.asarray(stats["neg_hist"], np.float64)
    n_pos, n_neg = pos.sum(), neg.sum()
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    u = (pos * (neg_below + 0.5 * neg)).sum()
    auroc = float(u / (n_pos * n_neg)) if n_pos > 0 and n_neg > 0 else 0.5
    den = float(stats["p_at_k_den"])
    p_at_k = float(stats["p_at_k_num"]) / den if den > 0 else 0.0
    return {"global_auroc": auroc, "global_p_at_k": p_at_k}
