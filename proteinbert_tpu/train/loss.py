"""Dual masked pretraining loss (reference utils.py:293-295, from logits).

The reference computes `mean(CE(local)·w) + mean(BCE(global)·w)` with a
double-softmax bug (probability-emitting heads into CrossEntropyLoss,
reference modules.py:277-293 + utils.py:293, SURVEY ledger #3). Here both
terms are computed from LOGITS via optax, and each term is a weighted mean
normalized by the weight mass (sum(w·loss)/sum(w)) rather than the
reference's mean-over-all-elements — so the loss scale is invariant to
padding fraction and annotation sparsity (documented divergence).

Weights follow the reference contract (reference data_processing.py:
175-176): local w = non-pad mask of the clean sequence; global w = 1 iff
the protein has any positive annotation.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax


def _weighted_mean(loss: jax.Array, w: jax.Array) -> jax.Array:
    return (loss * w).sum() / jnp.maximum(w.sum(), 1.0)


def pretrain_loss(
    local_logits: jax.Array,
    global_logits: jax.Array,
    targets: Dict[str, jax.Array],
    weights: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Total loss + per-term metrics.

    Args:
      local_logits: (B, L, V) fp32.
      global_logits: (B, A) fp32.
      targets: {"local": (B, L) int ids, "global": (B, A) 0/1}.
      weights: {"local": (B, L), "global": (B, A)} fp32 masks.
    """
    local_ce = optax.softmax_cross_entropy_with_integer_labels(
        local_logits, targets["local"]
    )
    local_loss = _weighted_mean(local_ce, weights["local"])

    global_bce = optax.sigmoid_binary_cross_entropy(
        global_logits, targets["global"]
    )
    global_loss = _weighted_mean(global_bce, weights["global"])

    total = local_loss + global_loss

    local_pred = local_logits.argmax(-1)
    local_acc = _weighted_mean(
        (local_pred == targets["local"]).astype(jnp.float32), weights["local"]
    )
    metrics = {
        "loss": total,
        "local_loss": local_loss,
        "global_loss": global_loss,
        "local_acc": local_acc,
    }
    return total, metrics
