"""Iteration-based pretraining loop (reference utils.py:220-345, TPU-native).

What changed vs the reference `pretrain()`:
- the whole device side of an iteration (corruption, fwd, bwd, clip,
  Adam, metrics) is ONE jitted `train_step` (train_state.py) — the
  reference crosses the host/device boundary several times per iteration
  (reference utils.py:287-301);
- under a mesh, batches are placed with a data-axis NamedSharding and the
  gradient all-reduce is compiled in by XLA (SURVEY C18 — the reference
  has no distributed path at all);
- checkpoints are orbax (sharded/async) and include RNG + data-iterator
  position (checkpoint.py), not a torch.save of partial state dicts;
- logging adds residues/sec/chip + MFU (metrics.py) to the reference's
  loss/LR/step-time line (reference utils.py:306-313).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.obs import as_telemetry
from proteinbert_tpu.train import train_state as ts
from proteinbert_tpu.train.checkpoint import Checkpointer
from proteinbert_tpu.train.metrics import DeviceMetricAccumulator, StepTimer
from proteinbert_tpu.train.resilience import (
    GracefulShutdown, check_finite, flush_inflight_checkpoint,
)

logger = logging.getLogger(__name__)


def _parse_fault_secs(secs_s):
    """Seconds for a drill knob, or ValueError. Rejects what time.sleep
    would crash or hang on (negative, NaN, inf): the drill contract is
    "malformed specs are ignored, not fatal" — a drill knob must never
    be able to kill an uncheckpointed run."""
    secs = float(secs_s)
    if not (0 <= secs < float("inf")):
        raise ValueError(secs_s)
    return secs


def _fault_stall_spec():
    """Observability-drill fault injection (VERDICT r4 item 3): parse
    PBT_FAULT_STALL_AT="<1-based step>:<seconds>" into (step, secs).
    The trainer sleeps that long at the top of the named step — INSIDE
    the timed window, like a real host-side stall (slow async-save
    serialization, input starvation, a tunnel hiccup) — so a drill can
    assert the window_* metrics and the slow-window summary localize it.
    Never set in production; the spec is logged loudly when active."""
    spec = os.environ.get("PBT_FAULT_STALL_AT")
    if not spec:
        return None
    try:
        step_s, _, secs_s = spec.partition(":")
        step = int(step_s)
        if step < 1:
            raise ValueError(spec)
        return step, _parse_fault_secs(secs_s)
    except ValueError:
        logger.warning("ignoring malformed PBT_FAULT_STALL_AT=%r", spec)
        return None


def _fault_eval_stall_secs():
    """Companion drill knob: PBT_FAULT_EVAL_STALL="<seconds>" sleeps
    inside every eval bracket — INSIDE the discounted region, so the
    drill can assert a slow eval does NOT masquerade as a training
    stall in the window metrics (the negative control for the
    PBT_FAULT_STALL_AT positive). Same ignore-malformed contract."""
    spec = os.environ.get("PBT_FAULT_EVAL_STALL")
    if not spec:
        return None
    try:
        return _parse_fault_secs(spec)
    except ValueError:
        logger.warning("ignoring malformed PBT_FAULT_EVAL_STALL=%r", spec)
        return None


def pretrain(
    cfg: PretrainConfig,
    batch_iterator,
    state: Optional[ts.TrainState] = None,
    checkpointer: Optional[Checkpointer] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    eval_batches=None,
    log_fn=None,
    telemetry=None,
) -> Dict[str, Any]:
    """Run the pretraining loop; returns {"state", "history", "perf"}.

    Args:
      cfg: full config (model/data/optimizer/train/checkpoint).
      batch_iterator: either an iterator of CLEAN {"tokens","annotations"}
        numpy batches (per-host shards under multi-host), or — preferred
        when resuming — a callable `(skip_batches: int) -> iterator` so a
        restored run can fast-forward the data stream without loading the
        already-consumed batches (see make_pretrain_iterator's
        skip_batches). A plain iterator on resume falls back to draining
        the consumed batches one by one.
      state: resume state; fresh-initialized if None (and restored from
        `checkpointer` if it has a saved step).
      checkpointer: optional; enables save/restore at
        cfg.checkpoint.every_steps cadence (reference utils.py:227,324).
      mesh: optional device mesh; batches are sharded over its 'data'
        axis (and train state per parallel/sharding.py rules).
      eval_batches: optional callable() -> iterator of held-out CLEAN
        batches; every cfg.train.eval_every steps they are scored with
        eval_step under a step-derived (deterministic) corruption key and
        the averaged metrics land in the history as eval_* (the held-out
        loop the reference's train/test dataloader split was built for
        but never ran, reference utils.py:71-107).
      log_fn: optional callable(step, metrics_dict) for external loggers.
      telemetry: optional obs.Telemetry — structured run events
        (run_start/step/ckpt_stage/eval/requeue/nan_halt/run_end),
        metrics registry, and flight recorder. None = the NULL facade:
        every instrumented site below becomes a no-op (~zero hot-path
        cost — all emits sit at log/eval/boundary cadence anyway).
    """
    tele = as_telemetry(telemetry)
    batches_consumed = 0
    # Eval-stream state. last_eval_loss feeds the eval-keyed plateau
    # (+inf = "no eval yet" — a fresh run replaces it with a seed eval
    # bracket below, so the plateau window never mixes train-scale
    # values; train_step's train-loss fallback remains as a net);
    # best/stalled drive early stopping. All three are CHECKPOINTED
    # (below, alongside batches_consumed) and restored here: resetting
    # them on resume would (a) let the post-resume steps feed train loss
    # into the restored reduce_on_plateau state — poisoning its
    # best_value with train-scale values in exactly the train<<eval
    # regime the feature targets — and (b) make early stop inert under
    # the exit-75 requeue loop (each requeue would restart the patience
    # counter from a fresh +inf baseline).
    last_eval_loss = np.float32(np.inf)
    best_eval_loss = float("inf")
    stalled_evals = 0
    if state is None:
        state = ts.create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
        if mesh is not None:
            # Place the fresh state per the sharding rules BEFORE any
            # restore: the checkpoint template's shardings tell orbax
            # where each shard goes (checkpoint.py:49-66) — restoring
            # into an unsharded template under a mesh would land the
            # whole state on one device (and under multi-host, make the
            # collective restore inconsistent). Also makes the fsdp/tp
            # intent of cfg.mesh actually apply to CLI-created states.
            from proteinbert_tpu.parallel.sharding import shard_train_state

            state = shard_train_state(state, mesh,
                                      zero_update=cfg.parallel.zero_update)
        if checkpointer is not None and checkpointer.latest_step() is not None:
            if tele.enabled:
                # A torn final checkpoint salvages to the previous step
                # with a note event (restore() docstring) — wired BEFORE
                # the restore so the fallback is on the run's record.
                checkpointer.on_note = lambda **f: tele.emit("note", **f)
            state, data_state = checkpointer.restore(state)
            batches_consumed = int((data_state or {}).get("batches_consumed", 0))
            es = (data_state or {}).get("eval_stream") or {}
            if es:
                # None encodes +inf (inf is not strict-JSON).
                last_eval_loss = np.float32(
                    es["last"] if es.get("last") is not None else np.inf)
                best_eval_loss = (float(es["best"])
                                  if es.get("best") is not None
                                  else float("inf"))
                stalled_evals = int(es.get("stalled", 0))
            logger.info("resumed from checkpoint at step %d (%d batches consumed)",
                        int(state.step), batches_consumed)

    def data_state_for(consumed: int) -> Dict[str, Any]:
        d: Dict[str, Any] = {"batches_consumed": consumed}
        if np.isfinite(last_eval_loss) or stalled_evals:
            d["eval_stream"] = {
                "last": (float(last_eval_loss)
                         if np.isfinite(last_eval_loss) else None),
                "best": (float(best_eval_loss)
                         if np.isfinite(best_eval_loss) else None),
                "stalled": stalled_evals,
            }
        return d

    if callable(batch_iterator):
        batch_iterator = batch_iterator(batches_consumed)
    elif batches_consumed:
        # Keep the resumed run on the same data stream position it would
        # have had uninterrupted (the reference replays from scratch,
        # reference utils.py:267-282).
        logger.warning(
            "resuming with a plain iterator: draining %d consumed batches "
            "(pass a factory to skip them for free)", batches_consumed)
        for _ in range(batches_consumed):
            next(batch_iterator)

    prefetch_it = None
    if cfg.data.prefetch_depth > 0:
        # Hide host-side batch production (HDF5 reads, tokenization)
        # behind the asynchronously-dispatched device step.
        from proteinbert_tpu.data.prefetch import prefetch

        batch_iterator = prefetch_it = prefetch(batch_iterator,
                                                cfg.data.prefetch_depth)

    put = _make_batch_put(mesh)

    # The implicit-SPMD jit handles every sharding EXCEPT the Pallas fused
    # kernel under sequence parallelism (a pallas_call is opaque to the
    # partitioner) — that combination runs the explicit shard_map step
    # (parallel/seq_parallel.py).
    from proteinbert_tpu.train.schedule import plateau_uses_eval

    eval_keyed_plateau = plateau_uses_eval(cfg.optimizer)
    if eval_keyed_plateau and (eval_batches is None
                               or not cfg.train.eval_every):
        raise ValueError(
            "optimizer.plateau_metric='eval_loss' needs a cadenced eval "
            "stream: pass eval_batches and set train.eval_every > 0")
    if cfg.train.early_stop_patience and (eval_batches is None
                                          or not cfg.train.eval_every):
        raise ValueError(
            "train.early_stop_patience needs a cadenced eval stream: "
            "pass eval_batches and set train.eval_every > 0")

    from proteinbert_tpu.parallel.zero import zero_extent

    zero_on = (mesh is not None and cfg.parallel.zero_update
               and zero_extent(mesh) > 1)
    if cfg.parallel.zero_update and not zero_on:
        logger.warning(
            "parallel.zero_update requested but %s — running the "
            "replicated update",
            "no mesh was passed" if mesh is None
            else "the mesh has data*fsdp == 1 (nothing to shard across)")
    if cfg.parallel.grad_reduce_dtype != "fp32" and not zero_on:
        # The quantized reduce-scatter (parallel/quant.py) only exists
        # on the zero-update path: without it there IS no cross-replica
        # gradient reduction to compress, and silently training at fp32
        # when the config asked for int8/bf16 wire would misreport
        # every comm claim downstream.
        logger.warning(
            "parallel.grad_reduce_dtype=%r has no effect without an "
            "active ZeRO-1 update (zero_update on a data*fsdp > 1 "
            "mesh) — the replicated step reduces gradients at fp32",
            cfg.parallel.grad_reduce_dtype)
    # plateau_step is the eval-keyed variant (extra plateau_value arg);
    # the zero step carries it natively, mirroring train_step.
    plateau_step = (lambda state, batch, v:               # noqa: E731
                    ts.train_step(state, batch, cfg, plateau_value=v))
    if mesh is not None and cfg.mesh.seq > 1 and cfg.model.use_pallas:
        from proteinbert_tpu.parallel.seq_parallel import (
            make_seq_parallel_train_step,
        )

        if eval_keyed_plateau:
            raise ValueError(
                "plateau_metric='eval_loss' is not supported with the "
                "explicit sequence-parallel pallas step (its shard_map "
                "step takes no plateau_value input)")
        seq_step = make_seq_parallel_train_step(mesh, cfg)
        step_fn = lambda state, batch, _cfg: seq_step(state, batch)  # noqa: E731
        logger.info("using explicit sequence-parallel train step (pallas%s)",
                    " + zero-update" if zero_on else "")
    elif zero_on:
        from proteinbert_tpu.parallel.zero import make_zero_train_step

        zero_step = make_zero_train_step(mesh, cfg)
        step_fn = lambda state, batch, _cfg: zero_step(state, batch)  # noqa: E731
        plateau_step = (lambda state, batch, v:           # noqa: E731
                        zero_step(state, batch, v))
        logger.info(
            "using ZeRO-1 sharded-update train step (update sharded over "
            "data*fsdp = %d replicas, grad reduction %s%s)",
            zero_extent(mesh), cfg.parallel.grad_reduce_dtype,
            "" if cfg.parallel.grad_reduce_dtype == "fp32"
            else " — quantized reduce-scatter wire, parallel/quant.py")
    else:
        step_fn = ts.train_step

    start_step = int(state.step)
    history: list = []

    if tele.enabled:
        if checkpointer is not None:
            # Checkpoint boundary lifecycle → ckpt_stage events, emitted
            # from wherever the save runs (incl. the stager thread:
            # EventLog is thread-safe).
            checkpointer.on_event = (
                lambda phase, save_step, **info:
                tele.emit("ckpt_stage", step=save_step, phase=phase, **info))
        from proteinbert_tpu.configs.config import config_to_dict

        tele.emit(
            "run_start", step=start_step, config=config_to_dict(cfg),
            jax_version=jax.__version__, pid=os.getpid(),
            mesh=({str(k): int(v) for k, v in mesh.shape.items()}
                  if mesh is not None else None),
            n_chips=(int(mesh.size) if mesh is not None
                     else jax.device_count()),
            resumed=bool(batches_consumed), zero_update=bool(zero_on),
        )
        if mesh is not None:
            # Per-chip persistent state bytes under the sharding rules
            # (the ZeRO-1 HBM claim, from shapes alone — no allocation).
            try:
                from proteinbert_tpu.parallel.zero import per_chip_state_bytes

                abstract = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)
                for part, n in per_chip_state_bytes(
                        mesh, abstract,
                        zero_update=cfg.parallel.zero_update).items():
                    tele.metrics.gauge(
                        "per_chip_state_bytes", part=part).set(n)
            except Exception:
                logger.debug("per-chip state-bytes gauge failed",
                             exc_info=True)

    if eval_keyed_plateau and not np.isfinite(last_eval_loss):
        # Seed the plateau stream with ONE up-front eval bracket
        # (ADVICE r4): without it, the pre-first-eval steps feed TRAIN
        # losses into reduce_on_plateau's accumulation window via the
        # +inf fallback, and in the overfit regime this feature targets
        # (train << eval) that mixed-scale window seeds an unreachably
        # low best_value — a premature LR cut right after the first
        # real eval. One eval pass before the timer starts keeps every
        # observed value eval-scale from step 0. The in-step fallback
        # stays as a safety net for direct train_step callers.
        em = _evaluate(state, eval_batches(), put, cfg, start_step)
        last_eval_loss = np.float32(em["eval_loss"])
        best_eval_loss = min(best_eval_loss, float(em["eval_loss"]))
        history.append({"step": start_step, **em})
        tele.emit("eval", step=start_step, metrics=em, seed=True)
        logger.info("seed eval at step %d: eval loss %.4f (plateau "
                    "baseline)", start_step, em["eval_loss"])
        if log_fn is not None:
            log_fn(start_step, em)

    if (cfg.checkpoint.warm_start and checkpointer is not None
            and checkpointer.latest_step() is None):
        # Warm-start save (r3 collapse attribution, BASELINE.md): the
        # FIRST save of a run pays orbax directory init, thread-pool
        # spinup, and the first full device->host state fetch — in r3
        # that one-time cost landed inside the timed stream as the
        # 650-800 stretch. Paying it here, before the StepTimer
        # anchors, keeps the timed windows showing only the steady
        # per-boundary cost. Only on a PRISTINE directory: with any
        # checkpoint present the restore already walked the orbax
        # machinery, and orbax silently skips saves at step <=
        # latest_step anyway — the outcome is checked so "warm" is
        # never logged for a save that did not happen.
        if checkpointer.save(start_step, state, data_state_for(start_step)):
            checkpointer.wait()
            logger.info("warm-start checkpoint at step %d (pre-timer)",
                        start_step)
        else:
            logger.warning("warm-start save at step %d was skipped by "
                           "the checkpoint manager", start_step)

    n_chips = mesh.size if mesh is not None else jax.device_count()
    timer = StepTimer(
        cfg.model,
        batch=cfg.data.batch_size,
        seq_len=cfg.data.seq_len,
        n_chips=n_chips,
    )
    preempted = False
    early_stopped = False
    diagnostic_saved = False
    ckpt_since_log = False  # a save started since the last log point
    metrics = None
    # Overlapped boundaries: the checkpoint path needs every shard
    # addressable from this process (device_get assembles the snapshot
    # host-side); under multi-host the synchronous collective save is
    # the only correct path. The eval overlap is legal only when
    # nothing needs the eval value BEFORE the next train step — an
    # eval-keyed plateau feeds it into the optimizer and early stopping
    # decides the break at the boundary, so both keep the synchronous
    # bracket.
    overlap_ckpt = (checkpointer is not None and cfg.checkpoint.overlap
                    and jax.process_count() == 1)
    overlap_eval = (cfg.train.overlap_eval and not eval_keyed_plateau
                    and not cfg.train.early_stop_patience)
    pending_eval = None  # (1-based eval step, dispatch_eval handle)

    def drain_and_sync():
        # Force the enqueued steps to completion and fold the wait into
        # the timing window, so the returned perf summary is device
        # rate even when max_steps is not a multiple of log_every (the
        # in-loop log points do the same; this covers the tail).
        if metrics is not None:
            float(metrics["loss"])
            timer.sync()

    def flush_staged_overlap():
        # Join an in-flight staged save (the backpressure rule: at most
        # one stage, so a second boundary arriving mid-overlap waits
        # here — that wait is real stall and stays IN the timed window).
        # The seconds the stage ran hidden behind training go to the
        # overlap account; worker errors re-raise here.
        if checkpointer is None:
            return
        t0 = time.perf_counter()
        stats = checkpointer.flush_staged()
        if stats:
            stall = time.perf_counter() - t0
            timer.overlap(max(stats.get("overlap_s", 0.0) - stall, 0.0))

    def harvest_staged():
        # Non-blocking: fold a COMPLETED staged save into the overlap
        # account (worker errors surface here too, at the next log
        # point after the failure instead of silently never).
        if checkpointer is None:
            return
        stats = checkpointer.poll_staged()
        if stats:
            timer.overlap(stats.get("overlap_s", 0.0))

    def checked_save(save_step, save_state):
        # Orbax SILENTLY skips saves at step <= the directory's latest
        # (checkpoint.py) — at the preemption/early-stop/final sites a
        # skipped save must at least be loud, or a "state saved,
        # exiting" log could cover for lost progress (e.g. a run
        # started with an explicit `state` against a mismatched
        # directory whose newest checkpoint is ahead of it).
        flush_staged_overlap()  # ordering: one save writing at a time
        if not checkpointer.save(save_step, save_state,
                                 data_state_for(save_step)):
            logger.warning(
                "checkpoint save at step %d was SKIPPED by the manager "
                "(directory already holds a step >= %d) — state was NOT "
                "written", save_step, save_step)
            return False
        return True

    def resolve_pending_eval():
        # Land an overlap-dispatched eval bracket. Called right after
        # the NEXT train step's dispatch (so the single metrics fetch
        # waits only out the eval's remaining device time while the
        # train step is already queued behind it), and at any point
        # that needs the eval stream current (a checkpoint boundary's
        # data_state, the end of the run). The fetch wait is eval
        # device time, not training time — discounted exactly like the
        # synchronous bracket; the host-side reduction it pays for
        # (pooled ranking stats) runs while the device crunches the
        # queued train step.
        nonlocal pending_eval, last_eval_loss, best_eval_loss, stalled_evals
        if pending_eval is None:
            return
        e_step, handle = pending_eval
        pending_eval = None
        t0 = time.perf_counter()
        em, _, _ = resolve_eval(handle)
        timer.discount(time.perf_counter() - t0)
        history.append({"step": e_step, **em})
        tele.emit("eval", step=e_step, metrics=em, overlapped=True)
        logger.info(
            "step %d eval loss %.4f (local %.4f global %.4f) acc %.3f",
            e_step, em["eval_loss"], em["eval_local_loss"],
            em["eval_global_loss"], em["eval_local_acc"],
        )
        if log_fn is not None:
            log_fn(e_step, em)
        last_eval_loss = np.float32(em["eval_loss"])
        # Best/stalled bookkeeping stays identical to the synchronous
        # bracket so the checkpointed eval_stream state is byte-equal
        # between the two modes (early stopping itself is never active
        # here — it is part of the overlap legality gate above).
        if em["eval_loss"] < best_eval_loss - cfg.train.early_stop_min_delta:
            best_eval_loss = em["eval_loss"]
            stalled_evals = 0
        else:
            stalled_evals += 1

    fault_stall = _fault_stall_spec()
    if fault_stall:
        logger.warning("FAULT INJECTION ACTIVE: %.1fs stall at step %d "
                       "(PBT_FAULT_STALL_AT)", fault_stall[1],
                       fault_stall[0])
    fault_eval_stall = _fault_eval_stall_secs()
    if fault_eval_stall:
        logger.warning("FAULT INJECTION ACTIVE: %.1fs stall per eval "
                       "bracket (PBT_FAULT_EVAL_STALL)", fault_eval_stall)

    with GracefulShutdown(
        on_signal=((lambda signum: tele.dump_flight(f"signal_{signum}"))
                   if tele.enabled else None)
    ) as stop:
      for step in range(start_step, cfg.train.max_steps):
        batch = next(batch_iterator)
        if fault_stall and step + 1 == fault_stall[0]:
            # Injected host stall, deliberately NOT discounted from the
            # timing window — the drill asserts it shows up there.
            time.sleep(fault_stall[1])
        if eval_keyed_plateau:
            state, metrics = plateau_step(state, put(batch), last_eval_loss)
        else:
            state, metrics = step_fn(state, put(batch), cfg)
        timer.update()
        # An overlap-dispatched eval bracket lands HERE — after this
        # step's dispatch, so its metrics fetch runs with the train
        # step already queued behind the eval on the device stream.
        resolve_pending_eval()
        if step - start_step + 1 == timer.warmup_steps:
            # Guaranteed drain at the warmup boundary: t0 was just
            # anchored at host ENQUEUE time, with the compile/warmup
            # backlog still executing remotely. sync()'s re-anchor
            # branch moves t0 past that backlog — without this, a run
            # with log_every=0 and no eval/checkpoint cadence charges
            # compile time to the timed window, deflating perf.
            drain_and_sync()

        if step == start_step:
            # One-time HBM report once the step (incl. compile-time
            # buffers) is resident — the first thing to look at when a
            # bigger batch OOMs. CPU backends report no stats; silent.
            # Dispatch is async, so force the step to completion first
            # via a scalar fetch (on the tunneled single-chip setup even
            # block_until_ready does not await remote execution —
            # bench.py's sync note).
            from proteinbert_tpu.utils.profiling import device_memory_report

            float(metrics["loss"])
            stats = next((s for s in device_memory_report().values()
                          if "bytes_in_use" in s), None)
            if stats:
                logger.info(
                    "HBM after first step: %.2f GB in use (peak %.2f) "
                    "of %.2f GB",
                    stats["bytes_in_use"] / 1e9,
                    stats.get("peak_bytes_in_use", 0) / 1e9,
                    stats.get("bytes_limit", 0) / 1e9,
                )
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit"):
                    if k in stats:
                        tele.metrics.gauge(f"hbm_{k}").set(stats[k])

        if cfg.train.log_every and (step + 1) % cfg.train.log_every == 0:
            # ONE device_get for the whole metrics dict (per-key float()
            # paid ~10 tunnel roundtrips per log point).
            m = {k: float(v) for k, v in jax.device_get(metrics).items()}
            # That fetch drained the async dispatch queue through this
            # step — fold the wait into the timing window, else
            # summary() reports host enqueue rate.
            timer.sync()
            if cfg.train.on_nan != "off" and not check_finite(
                m, step + 1, mode="quiet"
            ):
                # Preserve the state BEFORE halting so the blow-up is
                # debuggable (reference: no failure handling at all,
                # SURVEY §5). Saved to a SIBLING directory, once: the
                # NaN state must never become the checkpoint a restart
                # resumes from, nor churn the retention window.
                if checkpointer is not None and not diagnostic_saved:
                    diag = Checkpointer(
                        checkpointer.directory + "-diagnostic",
                        max_to_keep=1, async_save=False)
                    diag.save(step + 1, state,
                              {**data_state_for(step + 1),
                               "non_finite": True})
                    diag.close()
                    diagnostic_saved = True
                    logger.warning("non-finite state preserved in %s",
                                   checkpointer.directory + "-diagnostic")
                tele.emit("nan_halt", step=step + 1, metrics=m,
                          mode=cfg.train.on_nan)
                if cfg.train.on_nan == "halt":
                    # About to raise: a staged snapshot mid-fetch is the
                    # newest durable state a requeued run could resume
                    # from — flush it before dying (best-effort; the
                    # NaN stays the reported cause).
                    flush_inflight_checkpoint(checkpointer,
                                              "non-finite halt")
                    tele.emit("run_end", step=step + 1, outcome="nan_halt",
                              perf=timer.summary())
                    tele.dump_flight("nan_halt")
                # Raises in halt mode; logs the warning in warn mode.
                check_finite(m, step + 1, mode=cfg.train.on_nan)
            harvest_staged()  # completed overlap lands in this record
            m.update(timer.summary())
            if checkpointer is not None:
                # Attribution flag, not a metric: 1.0 when a checkpoint
                # save overlapped this log window — still writing now OR
                # started since the last log point (the latch catches a
                # save that started AND finished inside the window,
                # which a point sample at the log instant would miss).
                m["ckpt_in_flight"] = float(checkpointer.in_flight()
                                            or ckpt_since_log)
                ckpt_since_log = False
            history.append({"step": step + 1, **m})
            if tele.enabled:
                # All telemetry sits at log cadence — the per-step hot
                # path stays untouched (overhead <1% of a log interval,
                # ~0 of a step).
                extra = {}
                reg = tele.metrics
                if prefetch_it is not None:
                    extra["data_wait_s"] = round(prefetch_it.wait_s, 4)
                    reg.gauge("data_wait_seconds").set(prefetch_it.wait_s)
                    reg.gauge("data_batches_total").set(prefetch_it.batches)
                try:
                    import resource
                    import sys as _sys

                    # ru_maxrss: kilobytes on Linux, BYTES on macOS.
                    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                    rss *= 1 if _sys.platform == "darwin" else 1024
                    extra["host_max_rss_bytes"] = rss
                    reg.gauge("host_max_rss_bytes").set(rss)
                except Exception:
                    pass  # non-POSIX host: RSS gauge just absent
                tele.emit("step", step=step + 1, metrics=m, **extra)
                reg.counter("steps_total").inc(cfg.train.log_every)
                reg.set_many(m)  # loss/acc + StepTimer summary as gauges
            logger.info(
                "step %d loss %.4f (local %.4f global %.4f) acc %.3f %s",
                step + 1, m["loss"], m["local_loss"], m["global_loss"],
                m["local_acc"],
                (f"{m['residues_per_sec_per_chip']:.0f} res/s/chip "
                 f"MFU {m['mfu']:.3f}"
                 # The since-last-log rate tells a live operator
                 # "currently slow" apart from "was slow once" — the
                 # cumulative MFU alone re-reports an old stall forever.
                 + (f" (window {m['window_mfu']:.3f})"
                    if "window_mfu" in m else "")) if "mfu" in m else "",
            )
            if log_fn is not None:
                log_fn(step + 1, m)

        if stop.requested:
            # Preemption (SIGTERM) / operator interrupt: checkpoint at the
            # completed step and exit cleanly; resume picks up exactly here.
            drain_and_sync()
            saved = False
            if checkpointer is not None:
                # An in-flight staged snapshot must land BEFORE the
                # exit-75 requeue — best-effort, so a stager failure
                # cannot turn a clean preemption into a crash.
                flush_inflight_checkpoint(
                    checkpointer, "preemption (SIGTERM/SIGINT)")
                saved = checked_save(step + 1, state)
                checkpointer.wait()
            logger.warning("preempted at step %d: %s, exiting", step + 1,
                           "state saved" if saved else "state NOT saved")
            tele.emit("requeue", step=step + 1,
                      reason=f"signal_{stop.signum}", saved=saved)
            # Second, fuller dump (the signal-time one fired mid-step):
            # now the flush/save outcome and the requeue record are in
            # the ring — the picture a post-mortem actually wants.
            tele.dump_flight(f"signal_{stop.signum}")
            preempted = True
            break

        if (
            eval_batches is not None
            and cfg.train.eval_every
            and (step + 1) % cfg.train.eval_every == 0
        ):
            # Drain BEFORE starting the eval bracket: otherwise the
            # eval's first device fetch waits out the enqueued train
            # steps and discount() below subtracts that real step time
            # from the window, inflating throughput/MFU. (The overlap
            # path needs the drain too — after it, the eval batches are
            # the ONLY queued device work, so the deferred resolve-time
            # fetch waits out eval compute alone and discounting it
            # cannot swallow real step time.)
            drain_and_sync()
            t_eval = time.perf_counter()
            if fault_eval_stall:
                # Injected INSIDE the discounted bracket: the drill
                # asserts this does NOT surface as a slow window.
                time.sleep(fault_eval_stall)
            if overlap_eval:
                # Overlapped bracket: dispatch every eval batch (host
                # prep + enqueue — discounted) and defer the metrics
                # fetch until after the next train step's dispatch; the
                # eval_step dispatches capture the boundary state's
                # buffers BEFORE the next (donating) train step reuses
                # them, so the results are exact. History/log records
                # and the eval-stream bookkeeping happen at resolve
                # time — identical values, one step later in the
                # stream. Keying stays by the 1-based boundary step, so
                # `evaluate --like-step` reproduces it either way.
                handle = dispatch_eval(
                    state, eval_batches(), put, cfg,
                    eval_base_key(cfg, step + 1), drain_every=0)
                timer.discount(time.perf_counter() - t_eval)
                pending_eval = (step + 1, handle)
            else:
                # Key the eval by the 1-based step recorded in history,
                # so `evaluate --like-step <history step>` reproduces it.
                with tele.span("eval_bracket", step=step + 1):
                    em = _evaluate(state, eval_batches(), put, cfg, step + 1)
                timer.discount(time.perf_counter() - t_eval)
                history.append({"step": step + 1, **em})
                tele.emit("eval", step=step + 1, metrics=em)
                logger.info(
                    "step %d eval loss %.4f (local %.4f global %.4f) "
                    "acc %.3f",
                    step + 1, em["eval_loss"], em["eval_local_loss"],
                    em["eval_global_loss"], em["eval_local_acc"],
                )
                if log_fn is not None:
                    log_fn(step + 1, em)
                last_eval_loss = np.float32(em["eval_loss"])
                if em["eval_loss"] < best_eval_loss - cfg.train.early_stop_min_delta:
                    best_eval_loss = em["eval_loss"]
                    stalled_evals = 0
                else:
                    stalled_evals += 1
                    if (cfg.train.early_stop_patience
                            and stalled_evals >= cfg.train.early_stop_patience):
                        # The regime shift the r3 sustained run exposed:
                        # eval rising while train loss falls. Checkpoint
                        # the state and stop — continuing only overfits
                        # further.
                        drain_and_sync()
                        if checkpointer is not None:
                            checked_save(step + 1, state)
                            checkpointer.wait()
                        logger.warning(
                            "early stop at step %d: eval_loss has not "
                            "improved for %d consecutive evals (best %.4f)",
                            step + 1, stalled_evals, best_eval_loss)
                        early_stopped = True
                        break

        if (
            checkpointer is not None
            and cfg.checkpoint.every_steps
            and (step + 1) % cfg.checkpoint.every_steps == 0
        ):
            if overlap_ckpt:
                # Overlapped boundary: no drain, no stop-the-world.
                # The on-device snapshot captures this step's state
                # before the next (donating) train step can reuse its
                # buffers; the stager thread runs the device→host fetch
                # + orbax write behind the train steps the loop keeps
                # dispatching. The eval stream must be current FIRST —
                # a same-step overlapped eval is still pending and its
                # values belong in this boundary's data_state (resume
                # must restore them byte-identically).
                resolve_pending_eval()
                with tele.span("ckpt_boundary_staged", step=step + 1):
                    flush_staged_overlap()  # backpressure: one stage in flight
                    snap = ts.snapshot_train_state(state)
                    checkpointer.save_staged(step + 1, snap,
                                             data_state_for(step + 1))
                ckpt_since_log = True
                # Deliberately NOT discounted: the snapshot dispatch +
                # thread handoff are the boundary's only in-window cost
                # (~ms). The hidden fetch+write seconds are credited to
                # the overlap account when the stage lands
                # (harvest/flush), so summary() reports them as
                # overlapped rather than vanishing.
            else:
                # Drain first (so the save's state reads don't swallow
                # real step time), then discount the save itself — host
                # serialization is not training time and must not
                # deflate the window when a later sync() extends it.
                drain_and_sync()
                t_save = time.perf_counter()
                with tele.span("ckpt_boundary_sync", step=step + 1):
                    checked_save(step + 1, state)
                ckpt_since_log = True
                timer.discount(time.perf_counter() - t_save)

    # An eval dispatched at the final step resolves here — before the
    # final save's data_state is built.
    resolve_pending_eval()
    if not preempted and not early_stopped:
        drain_and_sync()
        if checkpointer is not None:
            flush_staged_overlap()
            if checkpointer.latest_step() != cfg.train.max_steps:
                checked_save(cfg.train.max_steps, state)
            checkpointer.wait()

    perf = timer.summary()
    tele.emit("run_end", step=int(state.step),
              outcome=("preempted" if preempted
                       else "early_stopped" if early_stopped
                       else "completed"),
              perf=perf)
    return {"state": state, "history": history, "perf": perf,
            "preempted": preempted, "early_stopped": early_stopped}


def eval_base_key(cfg: PretrainConfig, step: int) -> jax.Array:
    """The corruption base key the periodic eval uses at `step` — public
    so the standalone `evaluate` CLI can reproduce a training run's
    eval_* history exactly (--like-step)."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed + 1), step)


def dispatch_eval(
    state, batches, put, cfg: PretrainConfig, base_key: jax.Array,
    max_batches: int = 0, drain_every: int = 8,
):
    """Dispatch eval_step over `batches` (each keyed by
    fold_in(base_key, batch_index) → reproducible) WITHOUT fetching the
    results; returns an opaque pending handle for resolve_eval.

    Per-batch metric scalars stay ON DEVICE; the accumulator fetches
    them in one device_get per drain (bounded memory + dispatch
    backpressure) instead of ~10 high-latency roundtrips per batch on
    the tunneled single-chip setup. drain_every=0 defers EVERY fetch to
    resolve time — the overlapped eval bracket's mode, where the single
    resolve-time fetch happens after the next train step has already
    been dispatched, so the host never stands still inside the bracket.
    Row-weighting and the pooled-key rename fold in at drain time on
    host (float64 numerics)."""
    if max_batches:
        # Cap BEFORE pulling: the for-loop must not fetch (and discard)
        # one extra batch's worth of HDF5 reads + tokenization.
        import itertools

        batches = itertools.islice(batches, max_batches)
    pooled = ("global_auroc", "global_p_at_k")
    acc = DeviceMetricAccumulator(drain_every=drain_every)
    rename = lambda k: f"{k}_batch_mean" if k in pooled else k  # noqa: E731
    rank_stats = None
    n = 0
    rows = 0
    for batch in batches:
        b_rows = len(next(iter(batch.values())))
        m = dict(ts.eval_step(state, put(batch),
                              jax.random.fold_in(base_key, n), cfg))
        stats = m.pop("ranking_stats")
        rank_stats = stats if rank_stats is None else jax.tree.map(
            lambda a, b: a + b, rank_stats, stats)
        acc.add(m, weight=b_rows, key_fn=rename)
        n += 1
        rows += b_rows
    return acc, rank_stats, n, rows


def resolve_eval(pending, prefix: str = "eval_"):
    """Fetch + reduce a dispatch_eval handle → (metrics, n, rows).

    Loss/accuracy metrics are the row-weighted mean of the per-batch
    values (weighting matters only when batch sizes differ — the
    standalone CLI's tail batch). The ranking metrics global_auroc /
    global_p_at_k are POOLED at the split level from each batch's
    mergeable sufficient statistics (loss.global_ranking_stats): a
    dataset micro-AUROC is a property of the joint score distribution,
    not a mean of per-batch AUROCs (VERDICT r2 Weak #5). The per-batch
    means of the exact in-batch values remain available, renamed
    *_batch_mean."""
    from proteinbert_tpu.train.loss import ranking_metrics_from_stats

    acc, rank_stats, n, rows = pending
    metrics = {f"{prefix}{k}": v / max(rows, 1)
               for k, v in acc.sums().items()}
    if rank_stats is not None:
        rank_stats = jax.device_get(rank_stats)
        metrics.update({f"{prefix}{k}": v for k, v in
                        ranking_metrics_from_stats(rank_stats).items()})
    return metrics, n, rows


def evaluate_batches(
    state, batches, put, cfg: PretrainConfig, base_key: jax.Array,
    prefix: str = "eval_", max_batches: int = 0,
):
    """Synchronous eval over `batches` → (metrics dict, n_batches,
    n_rows); dispatch_eval + resolve_eval in one call (the CLI
    `evaluate` path and the trainer's non-overlapped bracket)."""
    return resolve_eval(
        dispatch_eval(state, batches, put, cfg, base_key,
                      max_batches=max_batches),
        prefix)


def _evaluate(state, batches, put, cfg, step) -> Dict[str, float]:
    """Mean eval_step metrics over a held-out split; corruption key is
    derived from the step so evals are reproducible run-to-run."""
    metrics, _, _ = evaluate_batches(
        state, batches, put, cfg, eval_base_key(cfg, step))
    return metrics


def _make_batch_put(mesh: Optional[jax.sharding.Mesh]):
    """Host numpy batch → device array(s), data-sharded under a mesh."""
    if mesh is None:
        return lambda batch: batch
    from proteinbert_tpu.parallel.sharding import batch_sharding

    shardings = None

    def put(batch):
        nonlocal shardings
        if shardings is None:
            shardings = batch_sharding(mesh)
        if jax.process_count() > 1:
            return {
                k: jax.make_array_from_process_local_data(shardings[k], v)
                for k, v in batch.items()
            }
        return jax.device_put(
            batch, {k: shardings[k] for k in batch} if isinstance(batch, dict)
            else shardings
        )

    return put
