from proteinbert_tpu.train.loss import pretrain_loss
from proteinbert_tpu.train.schedule import make_schedule, make_optimizer, needs_loss_value
from proteinbert_tpu.train.train_state import (
    TrainState, create_train_state, snapshot_train_state, train_step,
    eval_step,
)
from proteinbert_tpu.train.metrics import (
    forward_flops, train_flops, peak_flops_per_chip, StepTimer,
)
from proteinbert_tpu.train.checkpoint import Checkpointer
from proteinbert_tpu.train.trainer import pretrain
from proteinbert_tpu.train.finetune import (
    FinetuneState, create_finetune_state, finetune, finetune_step,
    finetune_eval_step,
)

__all__ = [
    "pretrain_loss", "make_schedule", "make_optimizer", "needs_loss_value",
    "TrainState", "create_train_state", "snapshot_train_state",
    "train_step", "eval_step",
    "forward_flops", "train_flops", "peak_flops_per_chip", "StepTimer",
    "Checkpointer", "pretrain",
    "FinetuneState", "create_finetune_state", "finetune", "finetune_step",
    "finetune_eval_step",
]
