"""Training-loop failure detection + graceful preemption (SURVEY §5).

The reference has NO failure handling at training level — its only
resilience is checkpoint-resume and ETL-side counters (SURVEY §5 "Failure
detection / elastic recovery: None"). On TPU this matters: preemptible
capacity gets SIGTERM'd, and a bfloat16 run can NaN long before a human
looks at the logs. Two mechanisms, both wired into train/trainer.py:

- `GracefulShutdown`: installs SIGTERM/SIGINT handlers that set a flag;
  the trainer finishes the in-flight step, saves a checkpoint, and
  returns with `preempted=True` instead of dying mid-save. The second
  signal falls through to the previous handler (so a double Ctrl-C still
  kills a hung run).
- `check_finite`: host-side NaN/Inf detection on the (already fetched)
  logged metrics; on trigger the trainer saves a diagnostic checkpoint
  and raises `NonFiniteLossError` (cfg.train.on_nan="halt", default) or
  logs and continues ("warn").

Both paths interact with the overlapped checkpoint boundary: a staged
snapshot may be mid-flight (device→host fetch on the stager thread)
when the SIGTERM or the NaN lands, and it must be flushed to disk
before the exit-75 requeue / halt — `flush_inflight_checkpoint` is the
shared best-effort flush both trainer paths call.
"""

from __future__ import annotations

import math
import signal
from typing import Dict, Optional

import logging

logger = logging.getLogger(__name__)


class NonFiniteLossError(RuntimeError):
    """Loss or grad norm went NaN/Inf; a diagnostic checkpoint was saved."""


class GracefulShutdown:
    """Flag-setting SIGTERM/SIGINT trap, usable as a context manager.

    >>> with GracefulShutdown() as stop:
    ...     for step in range(n):
    ...         if stop.requested: break
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_signal=None):
        self._signals = signals
        self._previous: Dict[int, object] = {}
        self.requested = False
        self.signum: Optional[int] = None
        # Optional callable(signum) run at the FIRST signal, inside the
        # handler — the flight-recorder dump hook: even if the clean
        # preemption path later wedges (a hung collective, a stuck
        # stager join), forensics for the moment of the signal are
        # already on disk. Must be cheap and must not raise; errors are
        # swallowed so a broken hook cannot turn a clean preemption
        # into a crash.
        self._on_signal = on_signal

    def _handler(self, signum, frame):
        if self.requested:
            # Second signal: restore + re-raise through the old handler so
            # an operator can still force-kill a wedged run.
            prev = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            raise KeyboardInterrupt(f"second signal {signum}")
        self.requested = True
        self.signum = signum
        logger.warning(
            "signal %s received: finishing current step, then "
            "checkpoint + clean exit", signum)
        if self._on_signal is not None:
            try:
                self._on_signal(signum)
            except Exception:
                logger.exception("on_signal hook failed (continuing "
                                 "with the clean preemption path)")

    def __enter__(self):
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
            except ValueError:
                # Not the main thread (e.g. a test runner worker): degrade
                # to a never-triggered flag rather than crash.
                logger.debug("cannot trap signal %s off the main thread", s)
        return self

    def __exit__(self, *exc):
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        return False


def flush_inflight_checkpoint(checkpointer, context: str) -> None:
    """Best-effort flush of staged/async checkpoint work on a failure
    path (SIGTERM → exit-75 requeue, NaN halt): an overlapped boundary
    may have a snapshot mid-fetch when the run dies, and abandoning it
    would lose the newest durable state a requeued run could resume
    from. Flush errors are LOGGED, never raised — the original failure
    (the signal, the NaN) must stay the reported cause of death."""
    if checkpointer is None:
        return
    try:
        checkpointer.wait()
    except Exception:
        logger.exception(
            "flushing in-flight checkpoint state during %s failed "
            "(continuing with the original failure path)", context)


def check_finite(metrics: Dict[str, float], step: int, mode: str = "halt",
                 keys=("loss", "grad_norm")) -> bool:
    """True if the watched metrics are finite. On failure: raises
    NonFiniteLossError (mode='halt'), warns (mode='warn'), or just
    returns False (mode='quiet' — the caller decides, e.g. to save a
    diagnostic checkpoint before re-calling with 'halt')."""
    bad = [k for k in keys if k in metrics and not math.isfinite(metrics[k])]
    if not bad:
        return True
    if mode == "quiet":
        return False
    msg = (f"non-finite {'/'.join(bad)} at step {step}: "
           f"{ {k: metrics[k] for k in bad} }")
    if mode == "halt":
        raise NonFiniteLossError(msg)
    logger.warning("%s (on_nan=warn: continuing)", msg)
    return False
