"""Train state pytree + jitted train/eval steps.

The reference's training engine is an untyped bundle of loop locals —
model, optimizer, two schedulers, and an iteration counter scattered
through `pretrain()` (reference utils.py:220-345). Here the entire
training state is ONE pytree (params, opt_state, PRNG key, step), so it
jits, shards with a NamedSharding tree, and checkpoints (orbax) as a unit
— including the RNG key the reference forgets to checkpoint (SURVEY §5
checkpoint bullet).

`train_step` fuses, on device, everything the reference does across the
host/device boundary per iteration (reference utils.py:282-319):
corruption (host DataLoader workers there; `data/corruption.py` here),
forward, dual masked loss, backward, clip, Adam update, metrics. Under a
`jit` with a data-sharded batch, XLA inserts the gradient all-reduce over
the mesh automatically — the psum-over-ICI replacement for the torch DDP
the reference never had (SURVEY C18).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.data.corruption import corrupt_batch
from proteinbert_tpu.train.loss import (
    global_ranking_metrics, global_ranking_stats, pretrain_loss,
)
from proteinbert_tpu.train.schedule import (
    effective_lr, make_optimizer, needs_loss_value, plateau_uses_eval,
)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    key: jax.Array


def gradient_update(
    tx, params: Any, grads: Any, opt_state: Any,
    loss: Any = None, needs_value: bool = False,
) -> Tuple[Any, Any]:
    """Shared optimizer-apply: update → params + cast-preserving add.
    Single source of truth for the default, sequence-parallel
    (parallel/seq_parallel.py) and fine-tune (train/finetune.py) steps."""
    extra = {"value": loss} if needs_value else {}
    updates, opt_state = tx.update(grads, opt_state, params, **extra)
    params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
    return params, opt_state


def create_train_state(key: jax.Array, cfg: PretrainConfig) -> TrainState:
    k_init, k_state = jax.random.split(key)
    params = proteinbert.init(k_init, cfg.model)
    tx = make_optimizer(cfg.optimizer)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        key=k_state,
    )


@partial(jax.jit, static_argnames="cfg", donate_argnums=0)
def train_step(
    state: TrainState, batch: Dict[str, jax.Array], cfg: PretrainConfig,
    plateau_value: Any = None,
) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One fused pretraining step on CLEAN {"tokens","annotations"} batch.

    `plateau_value`: host-provided scalar the reduce_on_plateau transform
    observes INSTEAD of this step's train loss, when
    cfg.optimizer.plateau_metric == "eval_loss" (the trainer passes the
    latest cadenced eval loss; +inf means "no eval yet" and falls back
    to the train loss so the placeholder can't tick the patience
    counter). The trainer seeds the stream with an up-front eval
    bracket, so under `train()` the fallback never fires — it exists
    for direct callers of this function, and such callers should know
    the fallback mixes train-scale values into the plateau window
    (ADVICE r4)."""
    key, step_key = jax.random.split(state.key)
    X, Y, W = corrupt_batch(
        step_key,
        batch["tokens"],
        batch["annotations"],
        token_randomize_prob=cfg.data.token_randomize_prob,
        annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
        annotation_drop_prob=cfg.data.annotation_drop_prob,
        annotation_add_prob=cfg.data.annotation_add_prob,
    )
    pad_mask = W["local"] > 0

    def loss_fn(params):
        local_logits, global_logits = proteinbert.apply(
            params, X["local"], X["global"], cfg.model, pad_mask
        )
        return pretrain_loss(local_logits, global_logits, Y, W)

    grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)

    value = metrics["loss"]
    if plateau_uses_eval(cfg.optimizer) and plateau_value is not None:
        # +inf = "no eval yet": observe the train loss until the first
        # real eval value arrives, so the pre-eval steps cannot tick the
        # plateau's patience counter on a meaningless placeholder.
        pv = jnp.asarray(plateau_value, dtype=jnp.float32)
        value = jnp.where(jnp.isfinite(pv), pv, metrics["loss"])
    params, opt_state = gradient_update(
        make_optimizer(cfg.optimizer), state.params, grads, state.opt_state,
        value, needs_loss_value(cfg.optimizer),
    )

    metrics = dict(metrics)
    metrics["grad_norm"] = optax.global_norm(grads)
    metrics["lr"] = effective_lr(cfg.optimizer, opt_state, state.step)
    new_state = TrainState(
        step=state.step + 1, params=params, opt_state=opt_state, key=key
    )
    return new_state, metrics


@partial(jax.jit, static_argnames="cfg")
def eval_step(
    state: TrainState, batch: Dict[str, jax.Array], key: jax.Array,
    cfg: PretrainConfig,
) -> Dict[str, jax.Array]:
    """Corrupted-input eval with a caller-provided key (deterministic)."""
    X, Y, W = corrupt_batch(
        key,
        batch["tokens"],
        batch["annotations"],
        token_randomize_prob=cfg.data.token_randomize_prob,
        annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
        annotation_drop_prob=cfg.data.annotation_drop_prob,
        annotation_add_prob=cfg.data.annotation_add_prob,
    )
    pad_mask = W["local"] > 0
    local_logits, global_logits = proteinbert.apply(
        state.params, X["local"], X["global"], cfg.model, pad_mask
    )
    _, metrics = pretrain_loss(local_logits, global_logits, Y, W)
    # Ranking quality of the GO head — eval-only (kept out of the hot
    # train step; the trainer prefixes these with eval_). global_auroc /
    # global_p_at_k are the EXACT in-batch values; ranking_stats is the
    # mergeable histogram evaluate_batches pools into the split-level
    # metrics (a dataset AUROC is not a mean of batch AUROCs).
    metrics.update(global_ranking_metrics(
        global_logits, Y["global"], W["global"]))
    metrics["ranking_stats"] = global_ranking_stats(
        global_logits, Y["global"], W["global"])
    return metrics
