"""Train state pytree + jitted train/eval steps.

The reference's training engine is an untyped bundle of loop locals —
model, optimizer, two schedulers, and an iteration counter scattered
through `pretrain()` (reference utils.py:220-345). Here the entire
training state is ONE pytree (params, opt_state, PRNG key, step), so it
jits, shards with a NamedSharding tree, and checkpoints (orbax) as a unit
— including the RNG key the reference forgets to checkpoint (SURVEY §5
checkpoint bullet).

`train_step` fuses, on device, everything the reference does across the
host/device boundary per iteration (reference utils.py:282-319):
corruption (host DataLoader workers there; `data/corruption.py` here),
forward, dual masked loss, backward, clip, Adam update, metrics. Under a
`jit` with a data-sharded batch, XLA inserts the gradient all-reduce over
the mesh automatically — the psum-over-ICI replacement for the torch DDP
the reference never had (SURVEY C18).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

# Buffer-donation opt-out, honored by every donating step in the
# framework (train_step here, finetune_step, the explicit seq-parallel
# step). On jax 0.4.x, executables DESERIALIZED from the persistent
# compilation cache mis-handle donated buffers on the CPU backend —
# observed as both segfaults and silently dropped parameter updates;
# without donation the same warm-cache runs are bit-correct
# (tests/conftest.py documents the repro). The test harness therefore
# sets PBT_DISABLE_DONATION=1 and keeps the compile cache: donation is
# worthless on CPU smoke shapes but vital for HBM headroom on TPU, so
# it stays on by default. Read at import time — it must be set before
# the first `proteinbert_tpu` import to take effect.
DONATE_STATE = () if os.environ.get("PBT_DISABLE_DONATION") else (0,)

from proteinbert_tpu.configs import PretrainConfig
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.data.corruption import corrupt_batch, corrupt_packed_batch
from proteinbert_tpu.train.loss import (
    global_ranking_metrics, global_ranking_stats, packed_pretrain_loss,
    pretrain_loss,
)
from proteinbert_tpu.train.schedule import (
    effective_lr, make_optimizer, needs_loss_value, plateau_uses_eval,
)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    key: jax.Array


def gradient_update(
    tx, params: Any, grads: Any, opt_state: Any,
    loss: Any = None, needs_value: bool = False,
) -> Tuple[Any, Any]:
    """Shared optimizer-apply: update → params + cast-preserving add.
    Single source of truth for the default, sequence-parallel
    (parallel/seq_parallel.py), ZeRO-1 (parallel/zero.py) and fine-tune
    (train/finetune.py) steps."""
    extra = {"value": loss} if needs_value else {}
    updates, opt_state = tx.update(grads, opt_state, params, **extra)
    params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
    return params, opt_state


def corrupt_for_step(
    state: "TrainState", batch: Dict[str, jax.Array], cfg: PretrainConfig,
):
    """The pretraining step's front QUARTER — split the RNG key and
    corrupt the clean batch — shared by `corrupt_forward_grads` below
    and the quantized-reduction step (parallel/quant.py, whose forward/
    backward runs inside a shard_map but whose corruption must be the
    SAME implicit-SPMD ops on the same step key, so fp32-vs-quantized
    runs see identical masking and their deviation is quantization
    noise alone). Returns (next state key, X, Y, W, segment_ids|None);
    a batch carrying "segment_ids" is a PACKED batch (data/packing.py)
    and corrupts segment-aware."""
    key, step_key = jax.random.split(state.key)
    if "segment_ids" in batch:
        seg = batch["segment_ids"]
        X, Y, W = corrupt_packed_batch(
            step_key,
            batch["tokens"],
            seg,
            batch["annotations"],
            token_randomize_prob=cfg.data.token_randomize_prob,
            annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
            annotation_drop_prob=cfg.data.annotation_drop_prob,
            annotation_add_prob=cfg.data.annotation_add_prob,
        )
        return key, X, Y, W, seg
    X, Y, W = corrupt_batch(
        step_key,
        batch["tokens"],
        batch["annotations"],
        token_randomize_prob=cfg.data.token_randomize_prob,
        annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
        annotation_drop_prob=cfg.data.annotation_drop_prob,
        annotation_add_prob=cfg.data.annotation_add_prob,
    )
    return key, X, Y, W, None


def corrupt_forward_grads(
    state: "TrainState", batch: Dict[str, jax.Array], cfg: PretrainConfig,
) -> Tuple[jax.Array, Any, Dict[str, jax.Array]]:
    """The pretraining step's front half — split the RNG key, corrupt
    the clean batch, forward, loss, backward — shared verbatim by the
    default step below and the ZeRO-1 step (parallel/zero.py), so the
    corruption plumbing and loss contract cannot drift between them.
    Returns (next state key, grads, loss metrics).

    A batch carrying a "segment_ids" key is a PACKED batch
    (data/packing.py): corruption, model, and loss take the segment-
    aware path (per-segment annotation state + per-segment loss
    normalization), selected at trace time from the batch's pytree
    structure — no config flag needed on device."""
    key, X, Y, W, seg = corrupt_for_step(state, batch, cfg)
    if seg is not None:

        def loss_fn(params):
            local_logits, global_logits = proteinbert.apply(
                params, X["local"], X["global"], cfg.model,
                segment_ids=seg,
            )
            return packed_pretrain_loss(
                local_logits, global_logits, Y, W, seg)

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        return key, grads, metrics
    pad_mask = W["local"] > 0

    def loss_fn(params):
        local_logits, global_logits = proteinbert.apply(
            params, X["local"], X["global"], cfg.model, pad_mask
        )
        return pretrain_loss(local_logits, global_logits, Y, W)

    grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
    return key, grads, metrics


def plateau_observation(cfg_opt, metrics: Dict[str, jax.Array],
                        plateau_value: Any):
    """The value the plateau transform observes this step: the train
    loss, or — under an eval-keyed plateau with a finite caller-provided
    value — the latest cadenced eval loss (+inf means "no eval yet" and
    falls back to the train loss so the placeholder can't tick the
    patience counter). One definition for the default and ZeRO-1 steps."""
    value = metrics["loss"]
    if plateau_uses_eval(cfg_opt) and plateau_value is not None:
        pv = jnp.asarray(plateau_value, dtype=jnp.float32)
        value = jnp.where(jnp.isfinite(pv), pv, metrics["loss"])
    return value


@jax.jit
def copy_pytree(tree):
    """Jitted identity copy of a pytree — fresh XLA-produced buffers.

    Two consumers, one jit cache entry: snapshot_train_state (below)
    uses it to decouple a checkpoint snapshot from the donated live
    buffers, and Checkpointer.restore uses it to canonicalize
    orbax-restored arrays — on jax 0.4.37's CPU backend, restored
    arrays fed straight into a DONATING jitted step whose executable
    was DESERIALIZED from the persistent compilation cache segfault
    (minimal repro: orbax restore + donate_argnums + warm
    jax_compilation_cache_dir; remove any one, no crash). The copy
    re-materializes leaves as ordinary XLA outputs, which cached
    executables donate safely — device_put/host round-trips do NOT."""
    return jax.tree.map(jnp.copy, tree)


def snapshot_train_state(state: TrainState) -> TrainState:
    """On-device copy of the whole state pytree, dispatched asynchronously.

    The overlapped checkpoint boundary (trainer/checkpoint.py) needs a
    version of the state whose buffers the training stream can never
    touch: `train_step` donates its state argument, so the buffers of
    `state` are REUSED by the very next step — a background device→host
    fetch reading them directly would either race the overwrite or (at
    the Python level) hit jax's deleted-buffer guard. The jitted copy
    returns fresh buffers that capture exactly the boundary step's
    values; because dispatch is async, this call costs host-enqueue time
    only, and the copy itself is device-side memcpy ordered BEFORE the
    next train step on the stream. The staged saver then device_gets the
    copy from a worker thread while training keeps dispatching."""
    return copy_pytree(state)


def create_train_state(key: jax.Array, cfg: PretrainConfig) -> TrainState:
    k_init, k_state = jax.random.split(key)
    params = proteinbert.init(k_init, cfg.model)
    tx = make_optimizer(cfg.optimizer)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        key=k_state,
    )


@partial(jax.jit, static_argnames="cfg", donate_argnums=DONATE_STATE)
def train_step(
    state: TrainState, batch: Dict[str, jax.Array], cfg: PretrainConfig,
    plateau_value: Any = None,
) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One fused pretraining step on CLEAN {"tokens","annotations"} batch.

    `plateau_value`: host-provided scalar the reduce_on_plateau transform
    observes INSTEAD of this step's train loss, when
    cfg.optimizer.plateau_metric == "eval_loss" (the trainer passes the
    latest cadenced eval loss; +inf means "no eval yet" and falls back
    to the train loss so the placeholder can't tick the patience
    counter). The trainer seeds the stream with an up-front eval
    bracket, so under `train()` the fallback never fires — it exists
    for direct callers of this function, and such callers should know
    the fallback mixes train-scale values into the plateau window
    (ADVICE r4)."""
    key, grads, metrics = corrupt_forward_grads(state, batch, cfg)
    value = plateau_observation(cfg.optimizer, metrics, plateau_value)
    params, opt_state = gradient_update(
        make_optimizer(cfg.optimizer), state.params, grads, state.opt_state,
        value, needs_loss_value(cfg.optimizer),
    )

    metrics = dict(metrics)
    metrics["grad_norm"] = optax.global_norm(grads)
    metrics["lr"] = effective_lr(cfg.optimizer, opt_state, state.step)
    new_state = TrainState(
        step=state.step + 1, params=params, opt_state=opt_state, key=key
    )
    return new_state, metrics


@partial(jax.jit, static_argnames="cfg")
def eval_step(
    state: TrainState, batch: Dict[str, jax.Array], key: jax.Array,
    cfg: PretrainConfig,
) -> Dict[str, jax.Array]:
    """Corrupted-input eval with a caller-provided key (deterministic).

    Packed batches (a "segment_ids" key) are scored with the per-segment
    loss; the ranking metrics see each packed protein as its own row
    ((B, S, A) flattened to (B·S, A) — empty segment slots carry zero
    weight and are excluded by the metrics' own validity masks)."""
    if "segment_ids" in batch:
        seg = batch["segment_ids"]
        X, Y, W = corrupt_packed_batch(
            key,
            batch["tokens"],
            seg,
            batch["annotations"],
            token_randomize_prob=cfg.data.token_randomize_prob,
            annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
            annotation_drop_prob=cfg.data.annotation_drop_prob,
            annotation_add_prob=cfg.data.annotation_add_prob,
        )
        local_logits, global_logits = proteinbert.apply(
            state.params, X["local"], X["global"], cfg.model,
            segment_ids=seg,
        )
        _, metrics = packed_pretrain_loss(
            local_logits, global_logits, Y, W, seg)
        A = global_logits.shape[-1]
        flat = lambda a: a.reshape(-1, A)  # noqa: E731
        gl, gy, gw = (flat(global_logits), flat(Y["global"]),
                      flat(W["global"]))
        metrics.update(global_ranking_metrics(gl, gy, gw))
        metrics["ranking_stats"] = global_ranking_stats(gl, gy, gw)
        return metrics
    X, Y, W = corrupt_batch(
        key,
        batch["tokens"],
        batch["annotations"],
        token_randomize_prob=cfg.data.token_randomize_prob,
        annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
        annotation_drop_prob=cfg.data.annotation_drop_prob,
        annotation_add_prob=cfg.data.annotation_add_prob,
    )
    pad_mask = W["local"] > 0
    local_logits, global_logits = proteinbert.apply(
        state.params, X["local"], X["global"], cfg.model, pad_mask
    )
    _, metrics = pretrain_loss(local_logits, global_logits, Y, W)
    # Ranking quality of the GO head — eval-only (kept out of the hot
    # train step; the trainer prefixes these with eval_). global_auroc /
    # global_p_at_k are the EXACT in-batch values; ranking_stats is the
    # mergeable histogram evaluate_batches pools into the split-level
    # metrics (a dataset AUROC is not a mean of batch AUROCs).
    metrics.update(global_ranking_metrics(
        global_logits, Y["global"], W["global"]))
    metrics["ranking_stats"] = global_ranking_stats(
        global_logits, Y["global"], W["global"])
    return metrics
