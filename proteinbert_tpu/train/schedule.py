"""LR schedules + optimizer factory (reference utils.py:257-264, fixed).

The reference chains a LambdaLR linear warmup into ReduceLROnPlateau via
SequentialLR; plateau's `step()` needs a metric, so the post-warmup phase
would crash the run at iteration `warmup_duration` (SURVEY ledger #7 —
latent because the smoke run stops at 250). Here:

- "warmup_cosine": optax warmup_cosine_decay — the recommended default.
- "warmup_plateau": linear warmup composed with
  `optax.contrib.reduce_on_plateau`, the working version of what the
  reference intended; the plateau transform consumes the loss through
  optax's injected-hyperparams extra-args mechanism (pass `value=loss` to
  `update`). Per-step batch loss is NOISE, not signal — the transform
  averages `plateau_window` consecutive step losses into one observation
  (optax `accumulation_size`) and only `plateau_patience` consecutive
  windowed observations without relative improvement cut the LR, with a
  `plateau_cooldown` re-baselining period after each cut. With the
  defaults (window 100, patience 10) that is 1,000 steps of no windowed
  improvement — not 10 unlucky batches (round-1 behavior, VERDICT Weak
  #1).
- "constant": flat LR after warmup.

All variants are wrapped with global-norm clipping (reference
utils.py:136) and Adam(b1,b2) (reference dummy_tests.py:127-130).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from proteinbert_tpu.configs import OptimizerConfig


def make_schedule(cfg: OptimizerConfig):
    warmup = optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        )
    if cfg.schedule in ("warmup_plateau", "constant"):
        return optax.join_schedules(
            [warmup, optax.constant_schedule(cfg.learning_rate)],
            [cfg.warmup_steps],
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def _clip_by_known_norm(max_norm, g_norm) -> optax.GradientTransformation:
    """optax.clip_by_global_norm with the global norm SUPPLIED instead of
    recomputed from the updates tree. Needed by the ZeRO-1 sharded
    update (parallel/zero.py): inside the update shard_map each replica
    holds only a 1/(data*fsdp) slice of every gradient leaf, so an
    in-tree global_norm would measure the local shard, not the
    gradient — the caller computes the true norm on the full tree
    outside the shard_map (it already does, for the grad_norm metric)
    and passes it in. The clip formula and the EmptyState are copied
    from optax so numerics and opt_state STRUCTURE are identical to the
    replicated chain — checkpoints stay interchangeable across modes."""
    def update_fn(updates, state, params=None):
        del params
        trigger = jnp.squeeze(g_norm < max_norm)

        def clip_fn(t):
            return jax.lax.select(
                trigger, t, (t / g_norm.astype(t.dtype)) * max_norm)

        return jax.tree.map(clip_fn, updates), state

    return optax.GradientTransformation(
        lambda params: optax.EmptyState(), update_fn)


def make_optimizer(cfg: OptimizerConfig,
                   clip_norm_value=None) -> optax.GradientTransformation:
    """Clip → Adam(schedule) [→ plateau scaling]. Returns a transformation
    whose `update` accepts `value=` when schedule == 'warmup_plateau'.

    `clip_norm_value`: optional traced scalar — the gradients' TRUE
    global norm, pre-computed by the caller. When given, the clip stage
    uses it instead of measuring the updates tree (see
    _clip_by_known_norm); the chain structure is unchanged."""
    schedule = make_schedule(cfg)
    if cfg.weight_decay > 0:
        adam = optax.adamw(
            schedule, b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay
        )
    else:
        adam = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2)
    if clip_norm_value is None:
        clip = optax.clip_by_global_norm(cfg.grad_clip_norm)
    else:
        clip = _clip_by_known_norm(cfg.grad_clip_norm, clip_norm_value)
    chain = [clip, adam]
    if cfg.schedule == "warmup_plateau":
        chain.append(
            optax.contrib.reduce_on_plateau(
                factor=cfg.plateau_factor,
                patience=cfg.plateau_patience,
                accumulation_size=cfg.plateau_window,
                cooldown=cfg.plateau_cooldown,
            )
        )
    return optax.chain(*chain)


def needs_loss_value(cfg: OptimizerConfig) -> bool:
    """True if the optimizer's update requires `value=loss` (plateau)."""
    return cfg.schedule == "warmup_plateau"


def plateau_uses_eval(cfg: OptimizerConfig) -> bool:
    """True when the plateau transform observes the cadenced EVAL loss
    instead of per-step train loss — the metric-driven ReduceLROnPlateau
    the reference intended (utils.py:257-264) and could never run. The
    trainer then passes the latest eval loss into each train step as
    `plateau_value`."""
    if cfg.plateau_metric not in ("train_loss", "eval_loss"):
        raise ValueError(
            f"unknown plateau_metric {cfg.plateau_metric!r}; "
            "expected 'train_loss' or 'eval_loss'")
    return (cfg.schedule == "warmup_plateau"
            and cfg.plateau_metric == "eval_loss")


def effective_lr(cfg: OptimizerConfig, opt_state, step):
    """The LR in effect at update-count `step` — schedule value times the
    plateau transform's current scale when schedule == 'warmup_plateau'.
    Pure jnp arithmetic over opt_state leaves, so it runs inside the
    jitted train step; logged per step like the reference's per-iteration
    LR line (reference utils.py:306-313)."""
    lr = make_schedule(cfg)(step)
    if cfg.schedule == "warmup_plateau":
        # optax.chain state is a tuple aligned with the transform list;
        # reduce_on_plateau is always appended last for this schedule.
        lr = lr * opt_state[-1].scale
    return lr
