"""LR schedules + optimizer factory (reference utils.py:257-264, fixed).

The reference chains a LambdaLR linear warmup into ReduceLROnPlateau via
SequentialLR; plateau's `step()` needs a metric, so the post-warmup phase
would crash the run at iteration `warmup_duration` (SURVEY ledger #7 —
latent because the smoke run stops at 250). Here:

- "warmup_cosine": optax warmup_cosine_decay — the recommended default.
- "warmup_plateau": linear warmup composed with
  `optax.contrib.reduce_on_plateau`, the working version of what the
  reference intended; the plateau transform consumes the loss through
  optax's injected-hyperparams extra-args mechanism (pass `value=loss` to
  `update`). Per-step batch loss is NOISE, not signal — the transform
  averages `plateau_window` consecutive step losses into one observation
  (optax `accumulation_size`) and only `plateau_patience` consecutive
  windowed observations without relative improvement cut the LR, with a
  `plateau_cooldown` re-baselining period after each cut. With the
  defaults (window 100, patience 10) that is 1,000 steps of no windowed
  improvement — not 10 unlucky batches (round-1 behavior, VERDICT Weak
  #1).
- "constant": flat LR after warmup.

All variants are wrapped with global-norm clipping (reference
utils.py:136) and Adam(b1,b2) (reference dummy_tests.py:127-130).
"""

from __future__ import annotations

import optax

from proteinbert_tpu.configs import OptimizerConfig


def make_schedule(cfg: OptimizerConfig):
    warmup = optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        )
    if cfg.schedule in ("warmup_plateau", "constant"):
        return optax.join_schedules(
            [warmup, optax.constant_schedule(cfg.learning_rate)],
            [cfg.warmup_steps],
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    """Clip → Adam(schedule) [→ plateau scaling]. Returns a transformation
    whose `update` accepts `value=` when schedule == 'warmup_plateau'."""
    schedule = make_schedule(cfg)
    if cfg.weight_decay > 0:
        adam = optax.adamw(
            schedule, b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay
        )
    else:
        adam = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2)
    chain = [optax.clip_by_global_norm(cfg.grad_clip_norm), adam]
    if cfg.schedule == "warmup_plateau":
        chain.append(
            optax.contrib.reduce_on_plateau(
                factor=cfg.plateau_factor,
                patience=cfg.plateau_patience,
                accumulation_size=cfg.plateau_window,
                cooldown=cfg.plateau_cooldown,
            )
        )
    return optax.chain(*chain)


def needs_loss_value(cfg: OptimizerConfig) -> bool:
    """True if the optimizer's update requires `value=loss` (plateau)."""
    return cfg.schedule == "warmup_plateau"


def plateau_uses_eval(cfg: OptimizerConfig) -> bool:
    """True when the plateau transform observes the cadenced EVAL loss
    instead of per-step train loss — the metric-driven ReduceLROnPlateau
    the reference intended (utils.py:257-264) and could never run. The
    trainer then passes the latest eval loss into each train step as
    `plateau_value`."""
    if cfg.plateau_metric not in ("train_loss", "eval_loss"):
        raise ValueError(
            f"unknown plateau_metric {cfg.plateau_metric!r}; "
            "expected 'train_loss' or 'eval_loss'")
    return (cfg.schedule == "warmup_plateau"
            and cfg.plateau_metric == "eval_loss")


def effective_lr(cfg: OptimizerConfig, opt_state, step):
    """The LR in effect at update-count `step` — schedule value times the
    plateau transform's current scale when schedule == 'warmup_plateau'.
    Pure jnp arithmetic over opt_state leaves, so it runs inside the
    jitted train step; logged per step like the reference's per-iteration
    LR line (reference utils.py:306-313)."""
    lr = make_schedule(cfg)(step)
    if cfg.schedule == "warmup_plateau":
        # optax.chain state is a tuple aligned with the transform list;
        # reduce_on_plateau is always appended last for this schedule.
        lr = lr * opt_state[-1].scale
    return lr
