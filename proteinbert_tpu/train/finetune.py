"""Epoch-based fine-tuning engine (SURVEY C14, completed).

The reference's fine-tune `train()`/`test()` pair exists only as
commented-out code — epoch loop, CosineAnnealingLR, grad clip, pluggable
metric dict, per-epoch checkpoints (reference utils.py:348-493). This is
that design finished and made TPU-native:

- one jitted `finetune_step` per iteration (forward + masked task loss +
  backward + clip + Adam with warmup-cosine), trunk and head in one
  gradient — or trunk frozen via an optax mask (task.freeze_trunk);
- epoch-based loop with per-epoch eval and best-metric tracking, the
  epoch/eval structure of the reference's sketch (reference
  utils.py:442-458);
- task losses by TaskConfig.kind: masked softmax CE (per-residue),
  softmax CE (per-protein class), MSE (per-protein scalar), all from
  logits (the reference pairs probability heads with CE — SURVEY ledger
  #3 — never repeated here).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Dict, Iterable, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from proteinbert_tpu.configs import FinetuneConfig
from proteinbert_tpu.data.vocab import PAD_ID
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.train.metrics import DeviceMetricAccumulator
from proteinbert_tpu.train.schedule import make_optimizer, needs_loss_value
from proteinbert_tpu.train.train_state import DONATE_STATE, gradient_update

logger = logging.getLogger(__name__)


@flax.struct.dataclass
class FinetuneState:
    step: jax.Array
    params: Any          # {"trunk", "head"}
    opt_state: Any


def make_finetune_optimizer(cfg: FinetuneConfig) -> optax.GradientTransformation:
    tx = make_optimizer(cfg.optimizer)
    if cfg.task.freeze_trunk:
        # Mask the trunk subtree: its params get zero updates but remain
        # in the tree (so checkpoints and shardings see one structure).
        tx = optax.multi_transform(
            {"train": tx, "freeze": optax.set_to_zero()},
            param_labels=lambda params: {
                "trunk": jax.tree.map(lambda _: "freeze", params["trunk"]),
                "head": jax.tree.map(lambda _: "train", params["head"]),
            },
        )
    return tx


def create_finetune_state(
    key: jax.Array,
    cfg: FinetuneConfig,
    pretrained_trunk: Optional[Any] = None,
) -> FinetuneState:
    params = ft_model.init(key, cfg.model, cfg.task, pretrained_trunk)
    tx = make_finetune_optimizer(cfg)
    return FinetuneState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
    )


def task_loss(
    outputs: jax.Array, batch: Dict[str, jax.Array], kind: str
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Loss + metrics for one batch. `batch["labels"]`: (B, L) int for
    token_classification (pad positions ignored), (B,) int for
    sequence_classification, (B,) float for sequence_regression."""
    labels = batch["labels"]
    if kind == "token_classification":
        # Unlabeled positions are -1 (data/finetune_data.py): <sos>/<eos>,
        # padding, and any residue the source didn't label.
        w = ((batch["tokens"] != PAD_ID) & (labels >= 0)).astype(jnp.float32)
        safe = jnp.maximum(labels, 0)
        ce = optax.softmax_cross_entropy_with_integer_labels(outputs, safe)
        denom = jnp.maximum(w.sum(), 1.0)
        loss = (ce * w).sum() / denom
        acc = ((outputs.argmax(-1) == safe) * w).sum() / denom
        return loss, {"loss": loss, "accuracy": acc}
    if kind == "sequence_classification":
        ce = optax.softmax_cross_entropy_with_integer_labels(outputs, labels)
        loss = ce.mean()
        acc = (outputs.argmax(-1) == labels).mean().astype(jnp.float32)
        return loss, {"loss": loss, "accuracy": acc}
    if kind == "sequence_regression":
        pred = outputs[..., 0]
        err = pred - labels.astype(jnp.float32)
        loss = (err ** 2).mean()
        return loss, {"loss": loss, "mae": jnp.abs(err).mean()}
    raise ValueError(f"unknown task kind {kind!r}")


@partial(jax.jit, static_argnames="cfg", donate_argnums=DONATE_STATE)
def finetune_step(
    state: FinetuneState, batch: Dict[str, jax.Array], cfg: FinetuneConfig
) -> Tuple[FinetuneState, Dict[str, jax.Array]]:
    def loss_fn(params):
        outputs = ft_model.apply(
            params, batch["tokens"], cfg.model, cfg.task,
            batch.get("annotations"),
        )
        return task_loss(outputs, batch, cfg.task.kind)

    grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
    params, opt_state = gradient_update(
        make_finetune_optimizer(cfg), state.params, grads, state.opt_state,
        metrics["loss"], needs_loss_value(cfg.optimizer),
    )
    return FinetuneState(step=state.step + 1, params=params,
                         opt_state=opt_state), metrics


@partial(jax.jit, static_argnames="cfg")
def finetune_eval_step(
    state: FinetuneState, batch: Dict[str, jax.Array], cfg: FinetuneConfig
) -> Dict[str, jax.Array]:
    outputs = ft_model.apply(
        state.params, batch["tokens"], cfg.model, cfg.task,
        batch.get("annotations"),
    )
    _, metrics = task_loss(outputs, batch, cfg.task.kind)
    return metrics


def evaluate(
    state: FinetuneState, batches: Iterable[Dict[str, Any]], cfg: FinetuneConfig
) -> Dict[str, float]:
    """Mean metrics over an eval split (the reference's test_step + metric
    aggregation, reference utils.py:171-217)."""
    # Per-batch scalars stay on device; drained in batched device_gets
    # (roundtrip-batching + dispatch backpressure + bounded memory —
    # see metrics.DeviceMetricAccumulator).
    acc = DeviceMetricAccumulator()
    for batch in batches:
        acc.add(finetune_eval_step(state, batch, cfg))
    n = acc.count
    return {k: v / max(n, 1) for k, v in acc.sums().items()}


def finetune(
    cfg: FinetuneConfig,
    train_batches,                      # callable(epoch) -> iterator of batches
    eval_batches=None,                  # callable() -> iterator, or None
    state: Optional[FinetuneState] = None,
    pretrained_trunk: Optional[Any] = None,
    checkpointer=None,                  # train.checkpoint.Checkpointer
    log_fn=None,
    telemetry=None,                     # obs.Telemetry (None = no-op)
    registry=None,                      # heads.HeadRegistry (opt-in: save
                                        # the trained head as a servable
                                        # artifact — ISSUE 8)
    register_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Epoch loop; returns {"state", "history", "best"} (+ "head_id"
    when a `registry` is given).

    `best` tracks the best eval epoch by accuracy (classification) or
    -loss (regression), and with a `checkpointer` each epoch's state is
    saved (epoch number as the step) — the per-epoch-checkpoint +
    model-selection design of the reference's sketch (reference
    utils.py:442-458).

    With `registry`, the trained head is saved as a content-addressed
    artifact carrying the fingerprint of the trunk it was ACTUALLY
    trained against (post-training — with freeze_trunk that equals the
    pretrained trunk, so the head serves directly over the resident
    trunk; without it the fingerprint records the co-trained trunk and
    serving over a different one raises the typed TrunkMismatchError
    instead of silently producing garbage), plus the best eval metrics;
    a `head_registered` event lands on the telemetry stream.
    """
    from proteinbert_tpu.obs import as_telemetry

    tele = as_telemetry(telemetry)
    start_epoch = 0
    history: list = []
    best: Dict[str, Any] = {"epoch": -1, "score": -float("inf")}
    if state is None:
        state = create_finetune_state(
            jax.random.PRNGKey(cfg.train.seed), cfg, pretrained_trunk
        )
        if checkpointer is not None and checkpointer.latest_step() is not None:
            # Resume an interrupted fine-tune: the saved step IS the
            # number of completed epochs, and the saved data carries the
            # pre-resume history + best so model selection still spans
            # the WHOLE run.
            start_epoch = checkpointer.latest_step()
            if start_epoch >= cfg.task.epochs:
                raise ValueError(
                    f"checkpoint dir {checkpointer.directory} already holds "
                    f"{start_epoch} completed epochs >= task.epochs="
                    f"{cfg.task.epochs}; use a fresh directory or raise "
                    "task.epochs to continue training")
            state, data = checkpointer.restore(state)
            data = data or {}
            history = list(data.get("history", []))
            best = dict(data.get("best", best))
            logger.info("resumed fine-tune after epoch %d", start_epoch)

    if tele.enabled:
        import os

        from proteinbert_tpu.configs.config import config_to_dict

        tele.emit("run_start", step=start_epoch, kind="finetune",
                  config=config_to_dict(cfg), jax_version=jax.__version__,
                  pid=os.getpid(), resumed=bool(start_epoch))

    for epoch in range(start_epoch, cfg.task.epochs):
        # Same roundtrip batching as evaluate(): the per-step float(v)
        # fetches made every training step synchronous with the device —
        # on the tunnel, epoch wall time was dominated by latency, not
        # compute. Drains are batched and memory-bounded.
        acc = DeviceMetricAccumulator()
        for batch in train_batches(epoch):
            state, metrics = finetune_step(state, batch, cfg)
            acc.add(metrics)
        n = acc.count
        record = {
            "epoch": epoch,
            **{f"train_{k}": v / max(n, 1) for k, v in acc.sums().items()},
        }

        if eval_batches is not None and (
            (epoch + 1) % cfg.task.eval_every_epochs == 0
            or epoch == cfg.task.epochs - 1
        ):
            with tele.span("finetune_eval", step=epoch + 1):
                em = evaluate(state, eval_batches(), cfg)
            record.update({f"eval_{k}": v for k, v in em.items()})
            tele.emit("eval", step=epoch + 1, metrics=em, kind="finetune")
            score = em.get("accuracy", -em.get("loss", float("inf")))
            if score > best["score"]:
                best = {"epoch": epoch, "score": score, **record}

        history.append(record)
        tele.emit("step", step=epoch + 1, metrics=record, kind="finetune")
        logger.info("finetune %s", record)
        if log_fn is not None:
            log_fn(epoch, record)
        if checkpointer is not None:
            checkpointer.save(epoch + 1, state,
                              {"history": history, "best": best})

    if checkpointer is not None:
        checkpointer.wait()

    head_id = None
    if registry is not None:
        import numpy as np

        from proteinbert_tpu.heads.registry import trunk_fingerprint

        # Fingerprint the trunk the head was trained AGAINST (the
        # post-training trunk: identical to the pretrained one under
        # freeze_trunk, the co-trained one otherwise) — the serving
        # side's compatibility check compares resident-trunk
        # fingerprints against exactly this value.
        fp = trunk_fingerprint(state.params["trunk"])
        metrics = {k: v for k, v in (history[-1] if history else {}).items()
                   if isinstance(v, (int, float))}
        metrics.update({k: v for k, v in best.items()
                        if k.startswith(("eval_", "train_"))
                        and isinstance(v, (int, float))})
        head_id = registry.save(
            jax.tree.map(np.asarray, state.params["head"]),
            cfg.task, fp, name=register_name, metrics=metrics,
            model={"local_dim": cfg.model.local_dim,
                   "global_dim": cfg.model.global_dim})
        tele.emit("head_registered", head_id=head_id, kind=cfg.task.kind,
                  name=register_name or head_id, trunk_fingerprint=fp,
                  metrics=metrics)
        logger.info("registered head %s (%s) in %s", head_id,
                    cfg.task.kind, registry.directory)

    # (emit sanitizes: a never-evaluated best's -inf score becomes null)
    tele.emit("run_end", outcome="completed", kind="finetune",
              perf={"best_epoch": best["epoch"],
                    "best_score": best["score"]})
    return {"state": state, "history": history, "best": best,
            "head_id": head_id}
