"""Step-time / throughput / MFU accounting.

The reference logs raw per-iteration wall-clock only (reference
utils.py:284,306-313). The north-star metric for this build is
residues/sec/chip and MFU (BASELINE.json), which needs an analytic FLOPs
model of the conv+attention hybrid — per-block shapes in SURVEY §3.4.

All matmul/conv terms count 2·MACs; training ≈ 3× forward (fwd + 2×bwd).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax

from proteinbert_tpu.configs import ModelConfig

# Peak dense FLOPs/s per chip (bf16), by jax device_kind substring.
PEAK_FLOPS = {
    "v5 lite": 197e12,     # TPU v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,     # TPU v6e (Trillium)
    "v6e": 918e12,
    "cpu": 5e11,           # nominal, for smoke-test MFU sanity only
}


def forward_flops(cfg: ModelConfig, batch: int, seq_len: int,
                  nonpad_tokens: Optional[float] = None) -> float:
    """Analytic forward-pass FLOPs (2·MACs) for one batch.

    `nonpad_tokens` (total real tokens in the batch, default B·L) makes
    the estimate reflect the ACTUAL per-batch work rather than the
    padded shape: every L-proportional term — the convs, the local
    dense/head, the attention K/V/score/sum — scales with real tokens,
    since pad FLOPs produce no useful output. This is the honest
    denominator for pad-adjusted MFU (bench.py --pack; ISSUE 4
    satellite): a 70%-pad batch at the padded count reports an MFU
    three times the useful-work utilisation.
    """
    B, L = batch, seq_len
    C, G, A = cfg.local_dim, cfg.global_dim, cfg.num_annotations
    H, k = cfg.num_heads, cfg.key_dim
    v = cfg.value_dim
    K = cfg.narrow_kernel
    # Total real-token count; L-proportional terms use T where the
    # padded-shape expression has B·L.
    T = float(B * L if nonpad_tokens is None else nonpad_tokens)

    per_block = (
        2 * T * K * C * C              # narrow conv (modules.py:126 analogue)
        + 2 * T * cfg.wide_kernel * C * C  # wide dilated conv
        + 2 * B * G * C                # global->local broadcast dense
        + 2 * T * C * C                # local residual dense
        + 2 * B * G * G                # global dense 1
        + 2 * B * H * G * k            # attention q
        + 2 * T * H * C * k            # attention K
        + 2 * T * H * C * v            # attention V
        + 2 * H * T * k                # scores
        + 2 * H * T * v                # weighted sum
        + 2 * B * G * G                # global dense 2
    )
    io = (
        2 * B * A * G                  # global input dense
        + 2 * T * C * cfg.vocab_size   # local head
        + 2 * B * G * A                # global head
    )
    return float(cfg.num_blocks * per_block + io)


def train_flops(cfg: ModelConfig, batch: int, seq_len: int,
                nonpad_tokens: Optional[float] = None) -> float:
    return 3.0 * forward_flops(cfg, batch, seq_len, nonpad_tokens)


def peak_flops_per_chip(device: Optional[jax.Device] = None) -> float:
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind.lower()
    for pat, val in PEAK_FLOPS.items():
        if pat in kind:
            return val
    return PEAK_FLOPS["cpu"]


class DeviceMetricAccumulator:
    """Sum per-batch DEVICE metric dicts without one device→host
    roundtrip per batch.

    Scalars stay on device; every `drain_every` add()s the pending
    dicts are fetched in ONE device_get and folded into host float
    sums. The drain doubles as dispatch backpressure (it blocks until
    those batches' computations finish) and bounds buffer growth to
    O(drain_every) — on the tunneled single-chip setup the per-scalar
    float(v) pattern this replaces paid ~10 high-latency roundtrips per
    batch across the trainer eval bracket and both fine-tune loops.
    Host-side float summation preserves float64 accumulation numerics.
    """

    def __init__(self, drain_every: int = 8):
        # drain_every=0 defers EVERY fetch to sums(): the overlapped-eval
        # dispatch path wants zero mid-loop device syncs (the single
        # resolve-time device_get is the only host block). Memory then
        # grows with the batch count — fine for eval splits, do not use
        # for unbounded streams.
        self.drain_every = drain_every
        self._pending: list = []
        self._sums: Dict[str, float] = {}
        self.count = 0

    def add(self, m: Dict[str, jax.Array], weight: float = 1.0,
            key_fn=None) -> None:
        self._pending.append((m, weight, key_fn))
        self.count += 1
        if self.drain_every and len(self._pending) >= self.drain_every:
            self._drain()

    def _drain(self) -> None:
        if not self._pending:
            return
        fetched = jax.device_get([m for m, _, _ in self._pending])
        for (_, w, key_fn), m in zip(self._pending, fetched):
            for k, v in m.items():
                key = key_fn(k) if key_fn else k
                self._sums[key] = self._sums.get(key, 0.0) + float(v) * w
        self._pending = []

    def sums(self) -> Dict[str, float]:
        self._drain()
        return dict(self._sums)


class StepTimer:
    """Wall-clock meter → steps/s, residues/s/chip, MFU.

    `update()` once per host-side step loop iteration; the first
    `warmup_steps` are excluded (compile + cache warmup).

    Each `summary()` reports TWO rates: the cumulative-since-warmup rate
    (the honest whole-run number) and a `window_*` rate covering only the
    steps since the previous `summary()` call. The window is what a live
    operator needs: a transient stall permanently depresses every later
    cumulative line (the round-3 sustained run re-reported one early
    stall for 4,000 steps — VERDICT r3 Weak #2), while the window rate
    recovers on the next log line and distinguishes "currently slow"
    from "was slow once". `summary()` therefore ADVANCES the window
    anchor — call it once per log cadence.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        batch: int,
        seq_len: int,
        n_chips: int = 1,
        warmup_steps: int = 2,
    ):
        self.flops_per_step = train_flops(cfg, batch, seq_len)
        self.residues_per_step = batch * seq_len
        self.n_chips = max(n_chips, 1)
        self.warmup_steps = warmup_steps
        self.peak = peak_flops_per_chip()
        self._count = 0
        self._t0 = None
        self._t_last = None
        self._steps_timed = 0
        # Window anchor: None means "window starts at _t0" (first window
        # after warmup); advanced to the last summary()'s snapshot after.
        self._win_t = None
        self._win_steps = 0
        # Overlap account: boundary seconds that ran HIDDEN behind the
        # train stream (staged checkpoint fetch+write). Unlike
        # discount(), these do NOT shift the anchors — the wall clock
        # never stopped for them, so the window stays honest with them
        # in; the account exists so the hidden cost is REPORTED (the
        # counterfactual stall a synchronous boundary would have paid),
        # not bookkept away.
        self._overlap_s = 0.0
        self._win_overlap_s = 0.0

    def discount(self, seconds: float) -> None:
        """Remove non-training wall time (an eval pass, a blocking save)
        from the measured interval so throughput/MFU stay honest."""
        if self._t0 is not None:
            self._t0 += seconds
            if self._win_t is not None:
                # The discounted wait also falls inside the current
                # window — shift its anchor the same way, else the
                # window charges the eval/save the cumulative rate
                # just excluded.
                self._win_t += seconds

    def overlap(self, seconds: float) -> None:
        """Record boundary work that executed CONCURRENTLY with training
        (a staged checkpoint's device→host fetch + write). The anchors
        do not move — hidden seconds cost no wall time — but summary()
        reports them (`overlap_s` / `window_overlap_s`) so the overlap
        win is measured, not assumed, and the wall-gap attribution tool
        can tell an overlapped boundary from a stop-the-world one."""
        if seconds > 0:
            self._overlap_s += seconds
            self._win_overlap_s += seconds

    def sync(self) -> None:
        """Extend the measured window to now. Call right after a
        device→host fetch that drained the dispatch queue: the per-step
        `update()` timestamps only measure host ENQUEUE rate (dispatch
        is async, and on the tunneled single-chip backend even
        block_until_ready does not await remote execution — bench.py's
        sync note), so without this the first log windows report
        enqueue throughput — physically impossible MFUs — not device
        throughput. A drain that lands before any step has been timed
        re-anchors the window START instead: the backlog being waited
        on there is compile/warmup work, which must not be charged to
        the first timed window."""
        if self._t0 is None:
            return
        if self._steps_timed:
            self._t_last = time.perf_counter()
        else:
            self._t0 = time.perf_counter()

    def update(self) -> None:
        self._count += 1
        if self._count == self.warmup_steps:
            self._t0 = time.perf_counter()
        elif self._count > self.warmup_steps:
            self._steps_timed = self._count - self.warmup_steps
            # Snapshot here, not in summary(): work done AFTER the last
            # step (final checkpoint save, host teardown) must not
            # deflate the reported throughput/MFU.
            self._t_last = time.perf_counter()

    def _rates(self, steps: int, dt: float, prefix: str) -> Dict[str, float]:
        steps_per_sec = steps / dt
        return {
            f"{prefix}steps_per_sec": steps_per_sec,
            f"{prefix}step_ms": 1000.0 / steps_per_sec,
            f"{prefix}residues_per_sec_per_chip": steps_per_sec
            * self.residues_per_step / self.n_chips,
            f"{prefix}mfu": steps_per_sec * self.flops_per_step
            / (self.peak * self.n_chips),
        }

    def summary(self) -> Dict[str, float]:
        if not self._steps_timed or self._t0 is None:
            return {}
        out = self._rates(self._steps_timed, self._t_last - self._t0, "")
        win_steps = self._steps_timed - self._win_steps
        win_dt = self._t_last - (self._win_t if self._win_t is not None
                                 else self._t0)
        if win_steps > 0 and win_dt > 0:
            out.update(self._rates(win_steps, win_dt, "window_"))
        if self._overlap_s:
            out["overlap_s"] = self._overlap_s
            out["window_overlap_s"] = self._win_overlap_s
        # Close the window: the next summary() measures from here.
        self._win_t = self._t_last
        self._win_steps = self._steps_timed
        self._win_overlap_s = 0.0
        return out
