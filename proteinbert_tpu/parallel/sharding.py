"""NamedSharding rules for train state and batches (SURVEY C18/C19).

The reference has no parallelism of any kind; these rules define how this
framework lays out the ProteinBERT train state and input batches over the
(data, fsdp, model, seq) mesh:

- batch tokens (B, L): B over (data, fsdp), L over seq — sequence
  parallelism enters at the input and propagates through the conv stack
  (XLA adds halo exchange) and the attention softmax (psum over seq).
- batch annotations (B, A): B over (data, fsdp); the 8943-dim annotation
  vector stays whole per example.
- params: tensor parallelism on the two A-sized matmuls — `global_head`
  kernel (G, A) column-sharded and `global_in` kernel (A, G) row-sharded
  over 'model' (the A dim is the big one, SURVEY §7 hard-part (e));
  everything else ≥2D is FSDP-sharded over 'fsdp' on its largest
  divisible axis (skipping the stacked-block leading N axis), scalars and
  vectors replicated.
- optimizer state: Adam's mu/nu mirror the params tree structure, so the
  same path-driven rule applies (their tree paths contain the param
  paths).

All rules are resolved from an ABSTRACT pytree (jax.eval_shape) so no
memory is allocated before shardings are known.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {
        "tokens": NamedSharding(mesh, P(("data", "fsdp"), "seq")),
        "annotations": NamedSharding(mesh, P(("data", "fsdp"), None)),
    }


def _path_has(path, name: str) -> bool:
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key == name:
            return True
    return False


def _leaf_spec(path, leaf, mesh: Mesh) -> P:
    shape = leaf.shape
    model_n = mesh.shape.get("model", 1)
    fsdp_n = mesh.shape.get("fsdp", 1)

    # Tensor parallelism over the annotation dimension A.
    if model_n > 1 and _path_has(path, "global_head"):
        if len(shape) >= 1 and shape[-1] % model_n == 0:
            return P(*([None] * (len(shape) - 1) + ["model"]))
    if model_n > 1 and _path_has(path, "global_in") and _path_has(path, "kernel"):
        if len(shape) >= 2 and shape[-2] % model_n == 0:
            return P(*([None] * (len(shape) - 2) + ["model", None]))

    # The token-embedding table is REPLICATED: at 26 x local_dim it is
    # a few KB at every preset, so FSDP-sharding it saves nothing — and
    # a feature-sharded table makes the token-lookup gather produce
    # feature-sharded (B, L, D) activations that must be resharded to
    # batch sharding, which the partitioner can only do by replicating
    # at fsdp extents > 2 (involuntary full remat on the gather; caught
    # by the 16-device tier, tests/test_parallel16.py).
    if _path_has(path, "embedding"):
        return P()

    # FSDP: shard one axis of big tensors; never the stacked-blocks
    # leading axis (it is num_blocks-sized). Stacked-block leaves take
    # the LAST divisible axis, not the largest: the lax.scan over blocks
    # slices them per iteration, and the SPMD partitioner's forward and
    # backward while-loops settle on a trailing-axis layout for the
    # sliced values — a largest-axis choice forced an involuntary
    # full-rematerialisation reshard between the two loops on every
    # fsdp-bearing mesh (VERDICT r2 Weak #3; reproduced and fixed by
    # this rule on the 8-device dryrun meshes). Non-scanned leaves keep
    # the largest-axis choice (more even splits for oblong matrices
    # like the (A, G) global_in kernel).
    if fsdp_n > 1 and len(shape) >= 2:
        if _path_has(path, "blocks"):
            axes = range(len(shape) - 1, 0, -1)
        else:
            axes = sorted(range(len(shape)), key=lambda i: shape[i],
                          reverse=True)
        for ax in axes:
            if shape[ax] % fsdp_n == 0 and shape[ax] >= 2 * fsdp_n:
                spec = [None] * len(shape)
                spec[ax] = "fsdp"
                return P(*spec)
    return P()


def state_sharding(mesh: Mesh, abstract_state: Any) -> Any:
    """NamedSharding pytree matching `abstract_state` (from jax.eval_shape)."""
    def rule(path, leaf):
        if not hasattr(leaf, "shape") or len(getattr(leaf, "shape", ())) == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _leaf_spec(path, leaf, mesh))

    return jax.tree_util.tree_map_with_path(rule, abstract_state)


def shard_train_state(state: Any, mesh: Mesh) -> Any:
    """Place a concrete TrainState onto the mesh per `state_sharding`."""
    shardings = state_sharding(mesh, jax.eval_shape(lambda: state))
    return jax.device_put(state, shardings)
