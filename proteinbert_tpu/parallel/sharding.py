"""NamedSharding rules for train state and batches (SURVEY C18/C19).

The reference has no parallelism of any kind; these rules define how this
framework lays out the ProteinBERT train state and input batches over the
(data, fsdp, model, seq) mesh:

- batch tokens (B, L): B over (data, fsdp), L over seq — sequence
  parallelism enters at the input and propagates through the conv stack
  (XLA adds halo exchange) and the attention softmax (psum over seq).
- batch annotations (B, A): B over (data, fsdp); the 8943-dim annotation
  vector stays whole per example.
- params: tensor parallelism on the two A-sized matmuls — `global_head`
  kernel (G, A) column-sharded and `global_in` kernel (A, G) row-sharded
  over 'model' (the A dim is the big one, SURVEY §7 hard-part (e));
  everything else ≥2D is FSDP-sharded over 'fsdp' on its largest
  divisible axis (skipping the stacked-block leading N axis), scalars and
  vectors replicated.
- optimizer state: Adam's mu/nu mirror the params tree structure, so the
  same path-driven rule applies (their tree paths contain the param
  paths). Under `parallel.zero_update` (ZeRO-1, parallel/zero.py) they
  additionally carry the joint ('data','fsdp') replica axis
  (zero_update_spec below) so each replica persists only a
  1/(data*fsdp) slice of the Adam moments.

All rules are resolved from an ABSTRACT pytree (jax.eval_shape) so no
memory is allocated before shardings are known.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {
        "tokens": NamedSharding(mesh, P(("data", "fsdp"), "seq")),
        # Packed batches (data/packing.py): the per-position segment map
        # shards exactly like the tokens it annotates; the per-segment
        # (B, S, A) annotation tensor keeps batch-only sharding (the
        # trailing spec axes replicate, so the 2D unpacked (B, A) shape
        # uses the same entry).
        "segment_ids": NamedSharding(mesh, P(("data", "fsdp"), "seq")),
        "annotations": NamedSharding(mesh, P(("data", "fsdp"), None)),
    }


def serve_batch_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    """Sharding for SERVED micro-batches (serve/dispatch.py): batch dim
    over the joint ('data','fsdp') replica axis, sequence dim
    replicated. Unlike training's `batch_sharding`, the L axis does NOT
    carry 'seq' — served batches are sliced to ragged bucket lengths
    that need not divide the seq extent, and a single forward pass has
    no optimizer state to amortize a halo exchange against; batch-dim
    data parallelism is the whole win.

    Ragged PACKED batches (serve/dispatch.RaggedDispatcher under a
    mesh) use the same rules: the per-position segment map shards
    exactly like the tokens it annotates, and the per-segment
    (rows, S, A) annotation tensor keeps batch-only sharding (trailing
    spec axes replicate, so the 2D bucketed (rows, A) shape uses the
    same entry)."""
    return {
        "tokens": NamedSharding(mesh, P(("data", "fsdp"), None)),
        "segment_ids": NamedSharding(mesh, P(("data", "fsdp"), None)),
        "annotations": NamedSharding(mesh, P(("data", "fsdp"), None)),
    }


def _path_has(path, name: str) -> bool:
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key == name:
            return True
    return False


def _leaf_spec(path, leaf, mesh: Mesh) -> P:
    shape = leaf.shape
    model_n = mesh.shape.get("model", 1)
    fsdp_n = mesh.shape.get("fsdp", 1)

    # Tensor parallelism over the annotation dimension A.
    if model_n > 1 and _path_has(path, "global_head"):
        if len(shape) >= 1 and shape[-1] % model_n == 0:
            return P(*([None] * (len(shape) - 1) + ["model"]))
    if model_n > 1 and _path_has(path, "global_in") and _path_has(path, "kernel"):
        if len(shape) >= 2 and shape[-2] % model_n == 0:
            return P(*([None] * (len(shape) - 2) + ["model", None]))

    # The token-embedding table is REPLICATED: at 26 x local_dim it is
    # a few KB at every preset, so FSDP-sharding it saves nothing — and
    # a feature-sharded table makes the token-lookup gather produce
    # feature-sharded (B, L, D) activations that must be resharded to
    # batch sharding, which the partitioner can only do by replicating
    # at fsdp extents > 2 (involuntary full remat on the gather; caught
    # by the 16-device tier, tests/test_parallel16.py).
    if _path_has(path, "embedding"):
        return P()

    # FSDP: shard one axis of big tensors; never the stacked-blocks
    # leading axis (it is num_blocks-sized). Stacked-block leaves take
    # the LAST divisible axis, not the largest: the lax.scan over blocks
    # slices them per iteration, and the SPMD partitioner's forward and
    # backward while-loops settle on a trailing-axis layout for the
    # sliced values — a largest-axis choice forced an involuntary
    # full-rematerialisation reshard between the two loops on every
    # fsdp-bearing mesh (VERDICT r2 Weak #3; reproduced and fixed by
    # this rule on the 8-device dryrun meshes). Non-scanned leaves keep
    # the largest-axis choice (more even splits for oblong matrices
    # like the (A, G) global_in kernel).
    if fsdp_n > 1 and len(shape) >= 2:
        if _path_has(path, "blocks"):
            axes = range(len(shape) - 1, 0, -1)
        else:
            axes = sorted(range(len(shape)), key=lambda i: shape[i],
                          reverse=True)
        for ax in axes:
            if shape[ax] % fsdp_n == 0 and shape[ax] >= 2 * fsdp_n:
                spec = [None] * len(shape)
                spec[ax] = "fsdp"
                return P(*spec)
    return P()


def param_spec(path, leaf, mesh: Mesh) -> P:
    """Public storage spec for one leaf (scalar-safe `_leaf_spec`) — the
    layout params keep BETWEEN steps, zero-update or not (the ZeRO-1
    path all-gathers updated params back to this spec every step)."""
    if not hasattr(leaf, "shape") or len(getattr(leaf, "shape", ())) == 0:
        return P()
    return _leaf_spec(path, leaf, mesh)


def zero_update_spec(path, leaf, mesh: Mesh) -> P:
    """ZeRO-1 spec for one leaf: the storage spec EXTENDED with the
    joint ('data','fsdp') replica axis (arXiv:2004.13336's cross-replica
    weight-update sharding, resolved per-leaf from the abstract tree).

    Used for two things that must agree element-for-element: the
    persistent sharding of Adam mu/nu (state_sharding with
    zero_update=True — the HBM win), and the in/out specs of
    parallel/zero.py's update shard_map (params/grads enter sliced the
    same way, so the update math on each shard lines up).

    Placement, in preference order: (1) widen an existing 'fsdp' axis to
    ('data','fsdp') — data-slicing an already-fsdp-sharded axis further
    is free at the shard_map boundary; (2) the largest spec-free axis
    divisible by data*fsdp; (3) the largest spec-free axis divisible by
    the data extent alone ('data' only, keeping any fsdp placement);
    (4) give up — the leaf stays at its storage spec and the update runs
    replicated across data (identical math on every replica; only small
    leaves land here, so the memory claim is unaffected)."""
    base = param_spec(path, leaf, mesh)
    shape = getattr(leaf, "shape", ())
    if len(shape) == 0:
        return base
    data_n = mesh.shape.get("data", 1)
    fsdp_n = mesh.shape.get("fsdp", 1)
    joint = data_n * fsdp_n
    if joint == 1:
        return base
    entries = list(base) + [None] * (len(shape) - len(base))
    for i, e in enumerate(entries):
        if e == "fsdp" and shape[i] % joint == 0:
            entries[i] = ("data", "fsdp")
            return P(*entries)
    has_fsdp = any(e == "fsdp" for e in entries)
    by_size = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    if not has_fsdp:
        for ax in by_size:
            if entries[ax] is None and shape[ax] % joint == 0:
                entries[ax] = ("data", "fsdp")
                return P(*entries)
    if data_n > 1:
        # 'fsdp' stays where the storage rule put it (a mesh axis can
        # appear in a spec only once); 'data' gets its own axis.
        for ax in by_size:
            if entries[ax] is None and shape[ax] % data_n == 0:
                entries[ax] = "data"
                return P(*entries)
    return base


def _is_opt_state_path(path) -> bool:
    if not path:
        return False
    p = path[0]
    key = getattr(p, "key", None)
    if key is None:
        key = getattr(p, "name", None)
    return key == "opt_state"


def state_sharding(mesh: Mesh, abstract_state: Any,
                   zero_update: bool = False) -> Any:
    """NamedSharding pytree matching `abstract_state` (from jax.eval_shape).

    zero_update=True applies the ZeRO-1 rule to OPTIMIZER-STATE leaves:
    Adam's mu/nu additionally carry the joint ('data','fsdp') axis
    (zero_update_spec), so each replica persists only a 1/(data*fsdp)
    slice of the Adam moments instead of a full fsdp-sharded copy.
    Params keep their ordinary storage spec either way — the zero step
    all-gathers them fresh every update, so their layout between steps
    is unchanged (and checkpoints stay shape-identical across modes)."""
    def rule(path, leaf):
        if not hasattr(leaf, "shape") or len(getattr(leaf, "shape", ())) == 0:
            return NamedSharding(mesh, P())
        if zero_update and _is_opt_state_path(path):
            return NamedSharding(mesh, zero_update_spec(path, leaf, mesh))
        return NamedSharding(mesh, _leaf_spec(path, leaf, mesh))

    return jax.tree_util.tree_map_with_path(rule, abstract_state)


def shard_train_state(state: Any, mesh: Mesh,
                      zero_update: bool = False) -> Any:
    """Place a concrete TrainState onto the mesh per `state_sharding`."""
    shardings = state_sharding(mesh, jax.eval_shape(lambda: state),
                               zero_update=zero_update)
    return jax.device_put(state, shardings)
