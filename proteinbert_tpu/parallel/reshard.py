"""Mesh-agnostic checkpoint resharding (ISSUE 11 tentpole, ROADMAP 2).

A checkpoint used to be implicitly married to the mesh shape that wrote
it: the trainer builds its restore template with the CURRENT run's
sharding rules, and nothing in the repo exercised — let alone
guaranteed — that a 4×2 run's state lands correctly on a 1-chip or
64-chip layout. This module makes topology an operational knob:

- **restore half**: orbax's StandardRestore places each leaf according
  to the restore TEMPLATE's shardings, not the writer's — so restoring
  any checkpoint onto any mesh is "build the template under the target
  mesh's `sharding.state_sharding` rules and restore". That covers the
  whole TrainState (params, ZeRO-1-sharded Adam mu/nu under
  `zero_update=True`, PRNG key, step) plus served trunks/heads (which
  restore through the same Checkpointer/inference path with a
  target-layout template).
- **schedule half**: a LIVE redistribution between two layouts of the
  same device set is one `with_sharding_constraint` — XLA lowers it to
  the portable collective schedule of the array-redistribution paper
  (PAPERS.md: all-gather / all-to-all / collective-permute composites).
  `reshard_schedule_bytes` AOT-compiles exactly that program and counts
  its wire bytes with the existing HLO byte-counter
  (`parallel.zero.collective_bytes_from_hlo`), so reshard traffic is
  byte-accounted the same way ZeRO's collectives are — and a later
  quantized variant (EQuARX line) A/Bs against these numbers. When the
  source and target device sets differ (e.g. 4×2 → a single chip), the
  move necessarily stages through the host and the schedule is
  reported as `host_staged` with zero collective bytes, not guessed.

`reshard_checkpoint` composes both into the `pbt reshard` CLI verb:
restore a run directory's latest (or given) step onto a target mesh,
save it into a fresh run directory whose config.json records the new
topology (so `pbt pretrain --checkpoint-dir` resumes there natively),
and emit a schema-versioned `reshard` event carrying the wire-byte
breakdown. Byte-identity across the round trip is asserted by
tests/test_reshard.py over a 1×1 ↔ 4×2 ↔ 8×1 grid, plain and ZeRO-1,
and by the tier-1 reshard smoke (tools/reshard_smoke.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from proteinbert_tpu.configs import MeshConfig

logger = logging.getLogger(__name__)


# ------------------------------------------------------------- mesh specs

def parse_mesh_spec(spec: str) -> MeshConfig:
    """Parse a CLI mesh spec into a MeshConfig.

    Accepted forms: `"4x2"` (data×fsdp), `"4x2x1x1"`
    (data×fsdp×model×seq), `"1"` (single device — no mesh), or
    key=value pairs `"data=4,fsdp=2"`. Axis order follows
    MeshConfig.axis_names.
    """
    spec = spec.strip().lower()
    if not spec:
        raise ValueError("empty mesh spec")
    def extent(raw) -> int:
        n = int(raw)
        if n < 1:
            # A zero/negative axis would silently degrade to the
            # single-device layout (num_devices 0 -> "no mesh") and
            # rewrite config.json with a nonsense topology — reject.
            raise ValueError(f"mesh axis extent must be >= 1, got {n}")
        return n

    if "=" in spec:
        axes: Dict[str, int] = {}
        for part in spec.split(","):
            if "=" not in part:
                raise ValueError(f"bad mesh spec fragment {part!r} "
                                 "(expected axis=extent)")
            k, v = part.split("=", 1)
            k = k.strip()
            if k not in MeshConfig().axis_names:
                raise ValueError(f"unknown mesh axis {k!r}; have "
                                 f"{MeshConfig().axis_names}")
            axes[k] = extent(v)
        return MeshConfig(**axes)
    dims = [extent(d) for d in spec.split("x")]
    if len(dims) > 4:
        raise ValueError(f"mesh spec {spec!r} has {len(dims)} axes; "
                         "at most data x fsdp x model x seq")
    dims += [1] * (4 - len(dims))
    return MeshConfig(data=dims[0], fsdp=dims[1], model=dims[2],
                      seq=dims[3])


def mesh_from_config(mesh_cfg: MeshConfig,
                     devices=None) -> Optional[Mesh]:
    """The Mesh a MeshConfig describes, or None for the single-device
    (unsharded) layout — the convention the trainer and CLI use."""
    if mesh_cfg.num_devices <= 1:
        return None
    from proteinbert_tpu.parallel.mesh import make_mesh

    if devices is None:
        devices = jax.devices()[: mesh_cfg.num_devices]
    return make_mesh(mesh_cfg, devices)


# ------------------------------------------------------- layout templates

def target_template(cfg, mesh: Optional[Mesh],
                    zero_update: bool = False) -> Any:
    """A concrete TrainState laid out for `mesh` under the sharding
    rules — the restore template whose shardings tell orbax where every
    shard of an arbitrary checkpoint goes. mesh=None → unsharded."""
    from proteinbert_tpu.parallel.sharding import shard_train_state
    from proteinbert_tpu.train.train_state import create_train_state

    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    if mesh is not None:
        state = shard_train_state(state, mesh, zero_update=zero_update)
    return state


def state_shardings_for(mesh: Optional[Mesh], abstract_state: Any,
                        zero_update: bool = False) -> Optional[Any]:
    """NamedSharding tree for `mesh` (None → None: unsharded)."""
    if mesh is None:
        return None
    from proteinbert_tpu.parallel.sharding import state_sharding

    return state_sharding(mesh, abstract_state, zero_update=zero_update)


def abstract_target_template(cfg, mesh: Optional[Mesh],
                             zero_update: bool = False) -> Any:
    """`target_template` without the allocation: ShapeDtypeStructs
    carrying the target layout's shardings. The restore path only
    needs shapes/dtypes/shardings, and a concrete template would cost
    a full extra copy of params + Adam moments in device memory right
    where memory is tightest (restoring a pod checkpoint on one chip).
    mesh=None pins every leaf to the default device explicitly — an
    UNSHARDED struct would let orbax fall back to the checkpoint's
    recorded (possibly absent-device) shardings."""
    from jax.sharding import SingleDeviceSharding
    from proteinbert_tpu.train.train_state import create_train_state

    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(cfg.train.seed),
                                   cfg))
    if mesh is None:
        single = SingleDeviceSharding(jax.devices()[0])
        return jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                              sharding=single), abstract)
    shardings = state_shardings_for(mesh, abstract,
                                    zero_update=zero_update)
    return jax.tree.map(
        lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                              sharding=sh),
        abstract, shardings)


# ------------------------------------------------------- live resharding

def reshard_state(state: Any, mesh: Optional[Mesh],
                  zero_update: bool = False) -> Any:
    """Redistribute a concrete TrainState onto `mesh` per the sharding
    rules (None = single-device). `jax.device_put` performs the move:
    same-device-set layout changes run the on-device collective
    schedule; cross-device-set moves stage through the host."""
    if mesh is None:
        return jax.device_put(state, jax.devices()[0])
    shardings = state_shardings_for(mesh, jax.eval_shape(lambda: state),
                                    zero_update=zero_update)
    return jax.device_put(state, shardings)


def _mesh_devices(mesh: Optional[Mesh]) -> Tuple:
    if mesh is None:
        return (jax.devices()[0],)
    return tuple(mesh.devices.flat)


def reshard_schedule_bytes(
    cfg,
    source_mesh: Optional[Mesh],
    target_mesh: Optional[Mesh],
    source_zero: bool = False,
    target_zero: bool = False,
) -> Tuple[Dict[str, int], str]:
    """Wire bytes of the source→target redistribution's collective
    schedule, from the compiled HLO alone (no state is allocated or
    moved). Returns (collective_bytes_from_hlo breakdown, schedule
    kind): `"collective"` when source and target share one device set —
    the AOT-compiled `with_sharding_constraint` program IS the portable
    redistribution schedule — or `"host_staged"` with zero bytes when
    the device sets differ and the move cannot stay on the fabric.
    `"identity"` when the layouts are the same (nothing moves)."""
    from proteinbert_tpu.parallel.zero import collective_bytes_from_hlo
    from proteinbert_tpu.train.train_state import create_train_state

    empty = {"total": 0}
    if set(_mesh_devices(source_mesh)) != set(_mesh_devices(target_mesh)):
        return empty, "host_staged"

    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg))
    src_sh = state_shardings_for(source_mesh, abstract,
                                 zero_update=source_zero)
    dst_sh = state_shardings_for(target_mesh, abstract,
                                 zero_update=target_zero)
    if source_mesh is None and target_mesh is None:
        return empty, "identity"

    if dst_sh is None:
        # Same single device on both sides (num_devices == 1 meshes).
        return empty, "identity"

    def move(tree):
        return jax.lax.with_sharding_constraint(tree, dst_sh)

    args = jax.tree.map(
        lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                              sharding=sh),
        abstract, src_sh) if src_sh is not None else abstract
    hlo = jax.jit(move).lower(args).compile().as_text()
    out = collective_bytes_from_hlo(hlo)
    return out, "collective" if out.get("total") else "identity"


# ------------------------------------------------------------- parity

def tree_digest(state: Any) -> Dict[str, bytes]:
    """Canonical per-leaf byte image of a pytree, keyed by tree path —
    layout-independent (device_get assembles the global array), so two
    layouts of the same state compare EQUAL iff byte-identical."""
    out: Dict[str, bytes] = {}

    def add(path, leaf):
        out[jax.tree_util.keystr(path)] = np.asarray(
            jax.device_get(leaf)).tobytes()

    jax.tree_util.tree_map_with_path(add, state)
    return out


def states_byte_identical(a: Any, b: Any) -> bool:
    return tree_digest(a) == tree_digest(b)


# ------------------------------------------------------ checkpoint verb

def reshard_checkpoint(
    src: str,
    dst: str,
    cfg=None,
    target_mesh_cfg: Optional[MeshConfig] = None,
    zero_update: Optional[bool] = None,
    step: Optional[int] = None,
    telemetry=None,
    verify: bool = True,
) -> Dict[str, Any]:
    """Restore `src`'s checkpoint onto the target mesh layout and save
    it into run directory `dst` (config.json updated to the new
    topology, so training/serving resume there natively).

    - `cfg`: the source run's config; default: `src/config.json`.
    - `target_mesh_cfg`: target topology; default: cfg.mesh (a layout-
      preserving copy).
    - `zero_update`: lay the optimizer state out ZeRO-1-sharded on the
      target (default: the source config's parallel.zero_update).
    - `verify`: re-restore from `dst` and byte-compare against the
      state just written (the round-trip parity gate).

    Returns a summary dict (step, meshes, wire_bytes, schedule, parity)
    and emits one `reshard` event when telemetry is enabled.
    """
    from proteinbert_tpu.configs import load_config, save_config
    from proteinbert_tpu.obs import as_telemetry
    from proteinbert_tpu.train.checkpoint import Checkpointer

    tele = as_telemetry(telemetry)
    if cfg is None:
        path = os.path.join(src, "config.json")
        if not os.path.isfile(path):
            raise FileNotFoundError(
                f"{src} has no config.json; pass cfg= (CLI: "
                "--preset/--set describing the source run)")
        cfg = load_config(path)
    if target_mesh_cfg is None:
        target_mesh_cfg = cfg.mesh
    if zero_update is None:
        zero_update = cfg.parallel.zero_update
    if target_mesh_cfg.num_devices > jax.device_count():
        raise ValueError(
            f"target mesh {target_mesh_cfg.shape} wants "
            f"{target_mesh_cfg.num_devices} devices, have "
            f"{jax.device_count()}")

    # The SOURCE mesh exists only for wire-byte accounting; restoring
    # never needs the writer's devices. On a host too small to build it
    # (the headline shrink case: a 4×2 checkpoint restored on one
    # chip), skip the schedule compile and report host_staged — which
    # is also the truth: the source layout's devices are not present.
    source_available = cfg.mesh.num_devices <= jax.device_count()
    source_mesh = mesh_from_config(cfg.mesh) if source_available else None
    target_mesh = mesh_from_config(target_mesh_cfg)

    template = abstract_target_template(cfg, target_mesh,
                                        zero_update=zero_update)
    src_ck = Checkpointer(src, async_save=False)
    src_ck.on_note = lambda **f: tele.emit("note", **f)
    try:
        state, data_state = src_ck.restore(template, step=step)
    finally:
        src_ck.close()
    if state is None:
        raise FileNotFoundError(f"no checkpoint found in {src}")
    restored_step = int(jax.device_get(state.step))

    if source_available:
        wire_bytes, schedule = reshard_schedule_bytes(
            cfg, source_mesh, target_mesh,
            source_zero=cfg.parallel.zero_update, target_zero=zero_update)
    else:
        wire_bytes, schedule = {"total": 0}, "host_staged"
    for kind, n in wire_bytes.items():
        tele.metrics.gauge("reshard_wire_bytes", kind=kind).set(n)

    new_cfg = cfg.replace(
        mesh=target_mesh_cfg,
        parallel=dataclasses.replace(cfg.parallel,
                                     zero_update=bool(zero_update)))
    dst_ck = Checkpointer(dst, async_save=False)
    try:
        saved = dst_ck.save(restored_step, state, data_state)
        if not saved:
            raise RuntimeError(
                f"{dst} already holds a checkpoint at step >= "
                f"{restored_step}; pick an empty/older output directory")
        parity = None
        if verify:
            back, _ = dst_ck.restore(template, step=restored_step,
                                     fallback=False)
            parity = states_byte_identical(state, back)
            if not parity:
                raise RuntimeError(
                    "round-trip parity FAILED: the state restored from "
                    f"{dst} is not byte-identical to the resharded "
                    "state just written")
    finally:
        dst_ck.close()
    save_config(new_cfg, os.path.join(os.path.abspath(dst), "config.json"))

    summary = {
        "step": restored_step,
        "source_mesh": {k: int(v) for k, v in
                        zip(cfg.mesh.axis_names, cfg.mesh.shape)},
        "target_mesh": {k: int(v) for k, v in
                        zip(target_mesh_cfg.axis_names,
                            target_mesh_cfg.shape)},
        "zero_update": bool(zero_update),
        "schedule": schedule,
        "wire_bytes": wire_bytes,
        "parity": parity,
    }
    tele.emit("reshard", step=restored_step,
              target_mesh=summary["target_mesh"],
              wire_bytes=wire_bytes,
              source_mesh=summary["source_mesh"],
              zero_update=bool(zero_update), schedule=schedule,
              parity=parity, src=os.path.abspath(src),
              dst=os.path.abspath(dst))
    logger.info(
        "resharded %s step %d: %s -> %s (%s schedule, %d collective "
        "bytes%s)", src, restored_step, summary["source_mesh"],
        summary["target_mesh"], schedule, wire_bytes.get("total", 0),
        ", parity verified" if parity else "")
    return summary
