"""Quantized collectives and int8 serving (EQuARX, arXiv:2506.17615).

Two quantization surfaces share this module because they share the
primitives (symmetric scales, stochastic rounding, int8 payloads):

**Training — the quantized reduce-scatter.** PR 2's
`parallel.grad_reduce_dtype="bf16"` rounded the ALREADY-REDUCED
gradients (numerics only): under the implicit-SPMD step the gradient
tensor carries a pending fp32 psum no cast may hoist ahead of, so the
wire still moved fp32. The quantized step here removes that wall by
computing PER-REPLICA partial gradients explicitly — corruption stays
in the implicit jit (same ops, same step key, so fp32-vs-quantized
runs corrupt identically), while the forward/backward runs inside a
`shard_map` over the joint ('data','fsdp') replica axis on the local
batch shard. The loss decomposes exactly: every term is a ratio of
global sums (train/loss.py), so with the weight-mass denominators
psum'd up front each replica's objective `local_numerator / D` sums to
the global loss, and its gradient is a true partial. The reduction is
then OURS to quantize:

  split each partial into one slice per destination replica along the
  leaf's zero-update axis (sharding.zero_update_spec — the SAME rule
  that lays out the persistent Adam moments, so the reduced shard
  lands exactly where the optimizer wants it)
  → quantize slices (bf16: stochastic round; int8: per-chunk symmetric
    scale + stochastic round, seeded from the step key + replica index
    — deterministic and multi-host lockstep by construction)
  → `all_to_all` the payloads (THIS is the wire: int8 moves ~4x fewer
    bytes than fp32, bf16 2x — verified from compiled HLO by
    `zero.collective_wire_bytes_from_hlo`, bench.py --comm)
  → dequantize + sum the n received slices = this replica's shard of
    the summed gradient
  → the SHARED optimizer-apply (train_state.gradient_update) on the
    1/(data*fsdp) shard, params all-gathered back to storage — both
    unchanged from parallel/zero.py.

Leaves whose zero-update spec is not a clean joint-axis slice (the
small replicated remainder of zero_update_spec's fallback) reduce by
plain fp32 psum — honest bytes, negligible share. The gradient-clip
norm is measured on the DEQUANTIZED summed gradient (the tensor the
optimizer actually consumes). `payload="fp32"` runs the identical
explicit reduce-scatter without rounding — the measurement baseline
bench.py --comm compares the quantized wire against, and the isolation
control for parity tests (harness error vs quantization error).

Restrictions (typed `QuantConfigError`): the explicit replica
shard_map replicates model/seq compute, so meshes with model>1 or
seq>1 are rejected, as is the explicit sequence-parallel Pallas step
(parallel/seq_parallel.py — mirroring its packing rejection); the
global batch must split evenly over data*fsdp.

**Serving — the int8 executable arm.** `quantize_params` rewrites
every >=2-D float leaf of a trunk as {q: int8, scale: fp32 per output
channel} (symmetric, deterministic round-to-nearest — serving stays
reproducible); 1-D leaves (biases, LN) stay fp32. The quantized jitted
entries dequantize INSIDE the executable, so HBM holds int8 weights
(~4x smaller trunk — the headroom ROADMAP item 5's two resident trunks
need) and XLA fuses the dequant into first use. `quant="int8_act"`
additionally fake-quantizes the trunk's output activations (dynamic
per-tensor int8) before the output heads — the opt-in activation arm.
Parity vs the fp32 arm is measured per request and surfaced
(serve/dispatch.py parity sampling, `serve_quant_parity_max`), and the
`heads_eval_score_min` downstream sentinel gates the quantized arm in
bench.py --heads so quantization can never silently degrade task
accuracy.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from proteinbert_tpu.configs import ModelConfig, PretrainConfig

ZERO_AXES = ("data", "fsdp")

# Payload dtypes of the explicit quantized reduce-scatter ("fp32" is
# the unrounded measurement/control baseline, not a config value).
WIRE_PAYLOADS = ("fp32", "bf16", "int8")

# Elements per int8 scale block: one fp32 scale per QUANT_CHUNK int8
# payload elements is <1% wire overhead while keeping a single outlier
# from crushing a whole slice's resolution.
QUANT_CHUNK = 512

# Serving quantization modes (configs.ServeConfig.quant / `pbt serve
# --quant`): fp32 = the ordinary executables; int8 = int8 weights,
# dequantized in-executable; int8_act = int8 weights + dynamic int8
# fake-quant of the trunk's output activations (opt-in).
SERVE_QUANT_MODES = ("fp32", "int8", "int8_act")


class QuantConfigError(ValueError):
    """A quantization knob was combined with a configuration that
    cannot honor it (unknown dtype/mode, model/seq-parallel mesh, the
    explicit seq-parallel Pallas step, indivisible batch)."""


# ----------------------------------------------------------- primitives


def stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """Stochastically round fp32 to bf16: add uniform 16-bit noise to
    the raw mantissa bits, then truncate to the bf16 (top-16-bit)
    pattern — P(round up) equals the discarded fraction, so the
    rounding is unbiased (the EQuARX requirement: biased rounding of
    gradient partials accumulates a systematic drift over replicas).
    Deterministic under a fixed key."""
    bits = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    u = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return lax.bitcast_convert_type(
        (u + bits) & jnp.uint32(0xFFFF0000), jnp.float32
    ).astype(jnp.bfloat16)


def quantize_int8_chunks(
    x: jax.Array, key: Optional[jax.Array],
    chunk: int = QUANT_CHUNK,
) -> Tuple[jax.Array, jax.Array, int]:
    """(..., m) fp32 → (int8 payload (..., k, chunk), fp32 scales
    (..., k), original m). Symmetric per-chunk scale amax/127; with a
    key the round is stochastic (unbiased — the training reduction),
    without it round-to-nearest (deterministic — serving weights)."""
    m = x.shape[-1]
    # Near-equal blocks instead of fixed-size blocks with a ragged
    # tail: k = ceil(m/chunk) blocks of ceil(m/k) elements pads < k
    # elements total, where a fixed 512 grid would pad a 576-element
    # slice by 78% (and a 16-element bias slice by 32x) — padding that
    # quietly eats the wire compression the payload buys.
    k = max(1, -(-m // chunk))
    chunk = -(-m // k)
    pad = k * chunk - m
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xc = x.reshape(x.shape[:-1] + (k, chunk))
    amax = jnp.max(jnp.abs(xc), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    y = xc / scale[..., None]
    if key is not None:
        y = jnp.floor(y + jax.random.uniform(key, y.shape))
    else:
        y = jnp.round(y)
    return jnp.clip(y, -127, 127).astype(jnp.int8), scale, m


def dequantize_int8_chunks(q: jax.Array, scale: jax.Array,
                           m: int) -> jax.Array:
    """Inverse of quantize_int8_chunks (trailing pad dropped)."""
    full = q.astype(jnp.float32) * scale[..., None]
    return full.reshape(full.shape[:-2] + (-1,))[..., :m]


# ------------------------------------------- quantized reduce-scatter


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def _leaf_plan(spec: P, shape: Tuple[int, ...], joint: int):
    """How one gradient leaf reduces: ("alltoall", dim) when its
    zero-update spec is a single clean ('data','fsdp') slice along
    `dim` (the quantized path), else ("psum", entries) — plain fp32
    psum, then a local slice to the spec's layout (the small
    fallback-leaf remainder; see module doc)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    rep = [i for i, e in enumerate(entries)
           if any(a in ZERO_AXES for a in _axes_of(e))]
    if (len(rep) == 1 and _axes_of(entries[rep[0]]) == ZERO_AXES
            and shape[rep[0]] % joint == 0
            and all(e is None for i, e in enumerate(entries)
                    if i != rep[0])):
        return ("alltoall", rep[0])
    return ("psum", tuple(entries))


def _replica_index(mesh: Mesh) -> jax.Array:
    """This device's linear index along the joint ('data','fsdp') axis
    — data-major, matching both shard_map's boundary slicing and
    all_to_all's destination order over the axis tuple."""
    idx = lax.axis_index("data")
    return idx * mesh.shape.get("fsdp", 1) + lax.axis_index("fsdp")


def _exchange(x: jax.Array) -> jax.Array:
    """all_to_all over the joint replica axis with optimization
    barriers pinning the payload DTYPE at the collective: without
    them, XLA's simplifier hoists the post-exchange dequant converts
    across the all-to-all (convert(all-to-all(q)) →
    all-to-all(convert(q))) and the wire silently moves fp32 again —
    the exact failure mode this module exists to remove (observed on
    the CPU backend; the barriers are identity ops, numerics
    untouched)."""
    x = lax.optimization_barrier(x)
    x = lax.all_to_all(x, ZERO_AXES, 0, 0, tiled=True)
    return lax.optimization_barrier(x)


def _reduce_scatter_leaf(g: jax.Array, dim: int, n: int, payload: str,
                         key: Optional[jax.Array]) -> jax.Array:
    """Inside the shard_map body: reduce this replica's full-shape
    partial `g` across the joint axis and return MY shard (slice along
    `dim`), with the wire carrying `payload`-typed slices."""
    x = jnp.moveaxis(g, dim, 0)
    lead, rest = x.shape[0], x.shape[1:]
    x = x.reshape(n, -1).astype(jnp.float32)
    m = x.shape[1]
    if payload == "int8":
        q, scale, _ = quantize_int8_chunks(x, key)
        q = _exchange(q)
        scale = _exchange(scale)
        red = (q.astype(jnp.float32) * scale[..., None]).sum(0)
        red = red.reshape(-1)[:m]
    elif payload == "bf16":
        q = stochastic_round_bf16(x, key)
        # Exchange the bf16 payload BITCAST to uint16: backends without
        # native bf16 (the CPU virtual meshes the byte evidence is
        # compiled on) float-normalize bf16 collectives up to f32,
        # which would silently double the wire; the u16 view is
        # bit-identical and integer-typed, so it survives every
        # backend's normalization passes at 2 bytes/element.
        q = lax.bitcast_convert_type(q, jnp.uint16)
        q = _exchange(q)
        q = lax.bitcast_convert_type(q, jnp.bfloat16)
        red = q.astype(jnp.float32).sum(0)
    else:  # fp32 — the unquantized explicit baseline
        red = _exchange(x).sum(0)
    red = red.reshape((lead // n,) + rest)
    return jnp.moveaxis(red, 0, dim)


def _slice_to_entries(x: jax.Array, entries, mesh: Mesh) -> jax.Array:
    """Slice a replicated (already-summed) leaf down to this device's
    shard per its spec entries — the psum-fallback leaves' exit."""
    for i, e in enumerate(entries):
        names = _axes_of(e)
        if not names:
            continue
        idx = jnp.int32(0)
        ext = 1
        for name in names:
            idx = idx * mesh.shape[name] + lax.axis_index(name)
            ext *= mesh.shape[name]
        size = x.shape[i] // ext
        x = lax.dynamic_slice_in_dim(x, idx * size, size, axis=i)
    return x


def check_quant_mesh(mesh: Mesh, payload: str,
                     batch_size: Optional[int] = None) -> int:
    """Validate a quantized-reduction request; returns the joint
    replica extent. Raises the typed QuantConfigError otherwise."""
    if payload not in WIRE_PAYLOADS:
        raise QuantConfigError(
            f"unknown quantized-reduction payload {payload!r}; "
            f"expected one of {WIRE_PAYLOADS}")
    joint = 1
    for ax in ZERO_AXES:
        joint *= mesh.shape.get(ax, 1)
    if joint <= 1:
        raise QuantConfigError(
            "quantized gradient reduction needs data*fsdp > 1 — there "
            "is no cross-replica reduction to compress on this mesh")
    for ax in ("model", "seq"):
        if mesh.shape.get(ax, 1) > 1:
            raise QuantConfigError(
                f"grad_reduce_dtype={payload!r} runs the forward/"
                f"backward inside an explicit data-parallel shard_map "
                f"and cannot shard the {ax!r} axis (extent "
                f"{mesh.shape[ax]}); use grad_reduce_dtype='fp32' (or "
                f"'bf16' numerics-only under the explicit seq-parallel "
                f"step) on model/seq-parallel meshes")
    if batch_size is not None and batch_size % joint:
        raise QuantConfigError(
            f"global batch {batch_size} does not split evenly over the "
            f"data*fsdp extent {joint} — the quantized step shards the "
            f"batch explicitly")
    return joint


@lru_cache(maxsize=8)
def make_quant_zero_train_step(mesh: Mesh, cfg: PretrainConfig,
                               payload: Optional[str] = None):
    """Jitted ZeRO-1 pretraining step whose gradient reduction is the
    explicit quantized reduce-scatter (module doc) — the
    `make_zero_train_step` route for grad_reduce_dtype in
    {"bf16","int8"}; `payload` overrides the wire dtype ("fp32" = the
    unrounded measurement baseline). Same signature and plateau_value
    contract as the fp32 zero step."""
    import optax

    from proteinbert_tpu.models import proteinbert
    from proteinbert_tpu.parallel.sharding import param_spec
    from proteinbert_tpu.parallel.zero import _update_specs
    from proteinbert_tpu.train import train_state as ts
    from proteinbert_tpu.train.loss import packed_segment_losses
    from proteinbert_tpu.train.schedule import (
        effective_lr, make_optimizer, needs_loss_value,
    )
    from proteinbert_tpu.utils.compat import shard_map

    payload = payload or cfg.parallel.grad_reduce_dtype
    joint = check_quant_mesh(mesh, payload, cfg.data.batch_size)
    opt_cfg = cfg.optimizer
    needs_value = needs_loss_value(opt_cfg)
    batch_spec = P(ZERO_AXES)

    def step(state: ts.TrainState, batch: Dict[str, jax.Array],
             plateau_value: Optional[jax.Array] = None):
        key, X, Y, W, seg = ts.corrupt_for_step(state, batch, cfg)
        # Noise stream for the stochastic rounding: derived from the
        # (replicated, checkpointed) state key, so re-runs and every
        # host of a multi-host run draw the same noise — fold_in
        # keeps it independent of the corruption stream.
        noise_key = jax.random.fold_in(key, 0x5172)
        p_specs = _update_specs(mesh, state.params)
        o_specs = _update_specs(mesh, state.opt_state)
        spec_leaves = jax.tree.leaves(
            p_specs, is_leaf=lambda x: isinstance(x, P))
        has_pv = plateau_value is not None
        value_arr = jnp.asarray(
            0.0 if plateau_value is None else plateau_value, jnp.float32)

        def body(params_full, params_sh, opt_sh, Xs, Ys, Ws, segs,
                 nkey, plateau_v):
            if segs is None:
                pad_mask = Ws["local"] > 0
                D_l = jnp.maximum(
                    lax.psum(Ws["local"].sum(), ZERO_AXES), 1.0)
                D_g = jnp.maximum(
                    lax.psum(Ws["global"].sum(), ZERO_AXES), 1.0)

                def loss_fn(p):
                    ll, gl = proteinbert.apply(
                        p, Xs["local"], Xs["global"], cfg.model, pad_mask)
                    ce = optax.softmax_cross_entropy_with_integer_labels(
                        ll, Ys["local"])
                    nl = (ce * Ws["local"]).sum()
                    bce = optax.sigmoid_binary_cross_entropy(
                        gl, Ys["global"])
                    ng = (bce * Ws["global"]).sum()
                    acc = ((ll.argmax(-1) == Ys["local"])
                           .astype(jnp.float32) * Ws["local"]).sum()
                    return nl / D_l + ng / D_g, (nl, ng, acc)
            else:
                # Packed rows: same decomposition over the per-segment
                # terms (packed_pretrain_loss is a weighted mean of
                # per-segment ratios whose masks are data-only).
                S = Ws["global"].shape[1]
                onehot = (segs[..., None] == jnp.arange(
                    1, S + 1, dtype=segs.dtype)).astype(jnp.float32)
                seg_valid = (jnp.einsum("bl,bls->bs", Ws["local"],
                                        onehot) > 0).astype(jnp.float32)
                seg_weighted = (Ws["global"].sum(-1) > 0).astype(
                    jnp.float32)
                D_l = jnp.maximum(
                    lax.psum(seg_valid.sum(), ZERO_AXES), 1.0)
                D_g = jnp.maximum(
                    lax.psum(seg_weighted.sum(), ZERO_AXES), 1.0)

                def loss_fn(p):
                    ll, gl = proteinbert.apply(
                        p, Xs["local"], Xs["global"], cfg.model,
                        segment_ids=segs)
                    terms = packed_segment_losses(ll, gl, Ys, Ws, segs)
                    nl = (terms["local"] * seg_valid).sum()
                    ng = (terms["global"] * seg_weighted).sum()
                    acc = (terms["local_acc"] * seg_valid).sum()
                    return nl / D_l + ng / D_g, (nl, ng, acc)

            grads, (nl, ng, acc) = jax.grad(
                loss_fn, has_aux=True)(params_full)
            nl = lax.psum(nl, ZERO_AXES)
            ng = lax.psum(ng, ZERO_AXES)
            acc = lax.psum(acc, ZERO_AXES)
            metrics = {
                "loss": nl / D_l + ng / D_g,
                "local_loss": nl / D_l,
                "global_loss": ng / D_g,
                "local_acc": acc / D_l,
            }

            # --- the quantized reduce-scatter, leaf by leaf -----------
            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            my_idx = _replica_index(mesh)
            reduced: List[jax.Array] = []
            sq_sharded = jnp.float32(0.0)
            sq_replicated = jnp.float32(0.0)
            for i, (g, spec) in enumerate(zip(g_leaves, spec_leaves)):
                kind, info = _leaf_plan(spec, g.shape, joint)
                if kind == "alltoall":
                    rk = jax.random.fold_in(
                        jax.random.fold_in(nkey, i), my_idx)
                    shard = _reduce_scatter_leaf(
                        g, info, joint, payload,
                        None if payload == "fp32" else rk)
                    sq_sharded = sq_sharded + (
                        shard.astype(jnp.float32) ** 2).sum()
                    reduced.append(shard)
                else:
                    full = lax.psum(g.astype(jnp.float32), ZERO_AXES)
                    sq_replicated = sq_replicated + (full ** 2).sum()
                    reduced.append(_slice_to_entries(full, info, mesh))
            grads_sh = jax.tree_util.tree_unflatten(treedef, reduced)
            # Clip norm of the DEQUANTIZED summed gradient — the tensor
            # the optimizer consumes (sharded leaves tile the full
            # tensor across replicas; psum'd leaves are whole already).
            g_norm = jnp.sqrt(
                lax.psum(sq_sharded, ZERO_AXES) + sq_replicated)

            value = ts.plateau_observation(
                opt_cfg, metrics, plateau_v if has_pv else None)
            tx = make_optimizer(opt_cfg, clip_norm_value=g_norm)
            new_p, new_o = ts.gradient_update(
                tx, params_sh, grads_sh, opt_sh, value, needs_value)
            return new_p, new_o, metrics, g_norm

        if seg is None:
            fn = shard_map(
                lambda pf, psh, osh, xs, ys, ws, nk, pv: body(
                    pf, psh, osh, xs, ys, ws, None, nk, pv),
                mesh=mesh,
                in_specs=(P(), p_specs, o_specs, batch_spec, batch_spec,
                          batch_spec, P(), P()),
                out_specs=(p_specs, o_specs, P(), P()),
                # Same rep/vma situation as the fp32 zero body: mixed
                # sharded/replicated outputs the checker cannot type;
                # parity with the replicated step is asserted by
                # tests/test_quant.py instead.
                check_vma=False,
            )
            new_params, new_opt, metrics, g_norm = fn(
                state.params, state.params, state.opt_state, X, Y, W,
                noise_key, value_arr)
        else:
            fn = shard_map(
                lambda pf, psh, osh, xs, ys, ws, sg, nk, pv: body(
                    pf, psh, osh, xs, ys, ws, sg, nk, pv),
                mesh=mesh,
                in_specs=(P(), p_specs, o_specs, batch_spec, batch_spec,
                          batch_spec, batch_spec, P(), P()),
                out_specs=(p_specs, o_specs, P(), P()),
                check_vma=False,
            )
            new_params, new_opt, metrics, g_norm = fn(
                state.params, state.params, state.opt_state, X, Y, W,
                seg, noise_key, value_arr)

        store = jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(
                mesh, param_spec(path, leaf, mesh)), new_params)
        new_params = lax.with_sharding_constraint(new_params, store)
        metrics = dict(metrics)
        metrics["grad_norm"] = g_norm
        metrics["lr"] = effective_lr(opt_cfg, new_opt, state.step)
        return ts.TrainState(step=state.step + 1, params=new_params,
                             opt_state=new_opt, key=key), metrics

    return jax.jit(step, donate_argnums=ts.DONATE_STATE)


# ------------------------------------------------- int8 serving weights


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x) == {"q", "scale"}


def quantize_params(params: Any) -> Any:
    """Symmetric per-output-channel int8 weight quantization of a trunk
    at load time: every float leaf with ndim >= 2 (dense/conv kernels,
    embeddings, the stacked block tensors) becomes {"q": int8,
    "scale": fp32} with the scale reduced over the leaf's INPUT axis
    (axis -2), keeping per-(stack/head, output-channel) resolution for
    the scanned block stacks; 1-D leaves (biases, LN scale/offset)
    stay fp32 — their bytes are noise and their dynamic range matters.
    Deterministic (round-to-nearest): the quantized arm serves
    reproducible outputs."""

    def quant(leaf):
        if (not hasattr(leaf, "ndim") or leaf.ndim < 2
                or not jnp.issubdtype(jnp.asarray(leaf).dtype,
                                      jnp.floating)):
            return leaf
        w = jnp.asarray(leaf, jnp.float32)
        amax = jnp.max(jnp.abs(w), axis=-2)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w / scale[..., None, :]),
                     -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale}

    return jax.tree.map(quant, params)


def dequantize_params(qparams: Any) -> Any:
    """Quantized tree → fp32 params, traceable (called INSIDE the
    quantized executables, so HBM holds the int8 form and XLA fuses
    the dequant into first use)."""

    def deq(x):
        if _is_quant_leaf(x):
            return x["q"].astype(jnp.float32) * x["scale"][..., None, :]
        return x

    return jax.tree.map(deq, qparams, is_leaf=_is_quant_leaf)


# The block weights the Pallas kernels dequantize IN-KERNEL (ISSUE 16):
# per-tile q·scale inside the one-pass / fused-segment / attention
# programs, so HBM ships int8 bytes on the serving fast path. Everything
# else (embeddings, heads, the block's global-side denses — consumed by
# plain XLA ops) keeps the HLO dequant.
_INKERNEL_QUANT_KEYS = (
    ("narrow_conv", "kernel"),
    ("wide_conv", "kernel"),
    ("local_dense", "kernel"),
    ("attention", "wq"),
    ("attention", "wk"),
    ("attention", "wv"),
)


def partial_dequantize_params(qparams: Any, use_pallas: bool = True) -> Any:
    """Quantized tree → the form the in-kernel-dequant serving arm
    consumes: every quant leaf is HLO-dequantized EXCEPT the block
    kernel weights the Pallas dispatches accept natively
    (`_INKERNEL_QUANT_KEYS` under "blocks"), which stay {"q": int8,
    "scale": fp32} so the kernels load int8 into VMEM and dequantize
    per-tile. With `use_pallas=False` no kernel ever sees the tree, so
    this degenerates to the full `dequantize_params` (the XLA reference
    path computes from HLO-dequantized weights either way — the kernel
    dispatch fallbacks do the same dequant themselves)."""
    if not use_pallas:
        return dequantize_params(qparams)

    def deq(path, x):
        if not _is_quant_leaf(x):
            return x
        keys = tuple(getattr(p, "key", None) for p in path)
        if "blocks" in keys and keys[-2:] in _INKERNEL_QUANT_KEYS:
            return x
        return x["q"].astype(jnp.float32) * x["scale"][..., None, :]

    return jax.tree_util.tree_map_with_path(deq, qparams,
                                            is_leaf=_is_quant_leaf)


def param_bytes(params: Any) -> int:
    """Total bytes of every array leaf — the HBM-footprint evidence for
    the quantized trunk (quant leaves count q + scale)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size"):
            total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total


def quantize_rows_int8(x) -> Tuple["np.ndarray", "np.ndarray"]:
    """Symmetric per-channel int8 quantization of a ROW BATCH — the
    store-side counterpart of `quantize_params` (same convention:
    amax/127 scales, deterministic round-to-nearest, zero-range
    channels pinned to scale 1.0). Host numpy on purpose: the neighbor
    index builder (proteinbert_tpu/index/) quantizes residual vectors
    while serializing blocks, where byte-identical re-runs are part of
    the durability contract and device nondeterminism would break the
    chaos drill's byte-identity gate. Returns (codes int8 (n, d),
    scales fp32 (d,))."""
    import numpy as np
    x = np.asarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise QuantConfigError(
            f"quantize_rows_int8 expects (rows, channels), got shape "
            f"{x.shape}")
    amax = np.max(np.abs(x), axis=0) if x.shape[0] else \
        np.zeros(x.shape[1], np.float32)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    codes = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
    return codes, scale


def dequantize_rows_int8(codes, scale) -> "np.ndarray":
    """Inverse of quantize_rows_int8 (up to rounding): codes * scale,
    fp32. The offline/reference dequant — the jitted scorer fuses the
    same arithmetic into its executable."""
    import numpy as np
    return (np.asarray(codes, np.float32)
            * np.asarray(scale, np.float32)[None, :])


def fake_quant_act(x: jax.Array) -> jax.Array:
    """Dynamic per-tensor symmetric int8 fake-quantization (the opt-in
    activation arm): quantize-dequantize in the activation dtype, so
    the numerics are int8's while the executable layout is unchanged."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    return (jnp.clip(jnp.round(xf / scale), -127, 127) * scale).astype(
        x.dtype)


# Quantized jitted serving entries: thin wrappers that dequantize
# in-jit and inline the EXISTING entry bodies (inference.py /
# heads/apply.py), so the quantized arm cannot drift from the fp32
# arm's semantics. The act variants re-compose encode + output heads
# (models/proteinbert.apply is exactly that) with the trunk's output
# activations fake-quantized in between.


@partial(jax.jit, static_argnames="cfg")
def _q_encode_batch(qparams, tokens, annotations, cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._encode_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens,
                                   annotations, cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_go_probs_batch(qparams, tokens, annotations, cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._go_probs_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens,
                                     annotations, cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_residue_probs_batch(qparams, tokens, annotations,
                           cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._residue_probs_batch(
        partial_dequantize_params(qparams, cfg.use_pallas),
                                          tokens, annotations, cfg)


def _act_logits(params, tokens, annotations, cfg: ModelConfig):
    """models/proteinbert.apply with the trunk outputs fake-quantized
    before the output heads (the activation arm's cut point); the pad
    mask derives from tokens exactly as apply's default does."""
    from proteinbert_tpu.models import proteinbert
    from proteinbert_tpu.ops.layers import dense_apply

    local, global_ = proteinbert.encode(params, tokens, annotations,
                                        cfg)
    local = fake_quant_act(local)
    global_ = fake_quant_act(global_)
    local_logits = dense_apply(params["local_head"],
                               local).astype(jnp.float32)
    global_logits = dense_apply(params["global_head"],
                                global_).astype(jnp.float32)
    return local, global_, local_logits, global_logits


@partial(jax.jit, static_argnames="cfg")
def _q_act_encode_batch(qparams, tokens, annotations, cfg: ModelConfig):
    from proteinbert_tpu.data.vocab import PAD_ID

    params = dequantize_params(qparams)
    local, global_, _, _ = _act_logits(params, tokens, annotations, cfg)
    mask = (tokens != PAD_ID).astype(jnp.float32)[:, :, None]
    local = local.astype(jnp.float32)
    return {
        "local_mean": (local * mask).sum(1)
        / jnp.maximum(mask.sum(1), 1.0),
        "global": global_.astype(jnp.float32),
    }


@partial(jax.jit, static_argnames="cfg")
def _q_act_go_probs_batch(qparams, tokens, annotations,
                          cfg: ModelConfig):
    params = dequantize_params(qparams)
    _, _, _, gl = _act_logits(params, tokens, annotations, cfg)
    return jax.nn.sigmoid(gl)


@partial(jax.jit, static_argnames="cfg")
def _q_act_residue_probs_batch(qparams, tokens, annotations,
                               cfg: ModelConfig):
    params = dequantize_params(qparams)
    _, _, ll, _ = _act_logits(params, tokens, annotations, cfg)
    return jax.nn.softmax(ll, -1)


@partial(jax.jit, static_argnames="cfg")
def _q_packed_encode_batch(qparams, tokens, segment_ids, annotations,
                           cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._packed_encode_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens, segment_ids, annotations,
        cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_packed_go_probs_batch(qparams, tokens, segment_ids, annotations,
                             cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._packed_go_probs_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens, segment_ids, annotations,
        cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_packed_residue_probs_batch(qparams, tokens, segment_ids,
                                  annotations, cfg: ModelConfig):
    from proteinbert_tpu import inference

    return inference._packed_residue_probs_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens, segment_ids, annotations,
        cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_trunk_batch(qparams, tokens, annotations, cfg: ModelConfig):
    from proteinbert_tpu.heads import apply as heads_apply

    return heads_apply.trunk_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens,
                                   annotations, cfg)


@partial(jax.jit, static_argnames="cfg")
def _q_packed_trunk_batch(qparams, tokens, segment_ids, annotations,
                          cfg: ModelConfig):
    from proteinbert_tpu.heads import apply as heads_apply

    return heads_apply.packed_trunk_batch(
        partial_dequantize_params(qparams, cfg.use_pallas), tokens, segment_ids, annotations,
        cfg)


def quant_entry(kind: str, act: bool = False):
    """The quantized executable for one request kind (bucketed path);
    predict_task trunks use `quant_trunk_entry`. Activation fake-quant
    is only defined for the pretrain kinds (heads trunks stay
    weight-only — documented in docs/serving.md)."""
    table = {
        ("embed", False): _q_encode_batch,
        ("predict_go", False): _q_go_probs_batch,
        ("predict_residues", False): _q_residue_probs_batch,
        ("embed", True): _q_act_encode_batch,
        ("predict_go", True): _q_act_go_probs_batch,
        ("predict_residues", True): _q_act_residue_probs_batch,
    }
    try:
        return table[(kind, act)]
    except KeyError:
        raise ValueError(f"no quantized entry for request kind "
                         f"{kind!r} (act={act})") from None


def quant_packed_entry(kind: str):
    table = {
        "embed": _q_packed_encode_batch,
        "predict_go": _q_packed_go_probs_batch,
        "predict_residues": _q_packed_residue_probs_batch,
    }
    try:
        return table[kind]
    except KeyError:
        raise ValueError(f"no quantized packed entry for request kind "
                         f"{kind!r}") from None
