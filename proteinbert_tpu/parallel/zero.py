"""ZeRO-1 cross-replica sharded weight update (arXiv:2004.13336).

On a mesh with a pure `data` axis, the default train step replicates
fp32 params AND the Adam mu/nu moments on every replica and pays a full
gradient all-reduce per step — the optimizer math is executed N times on
identical inputs, and 2x params of fp32 Adam state sits in every chip's
HBM. ZeRO-1 (Xu et al., *Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training*) removes the redundancy:

  reduce-scatter grads over ('data','fsdp')   [≈ the all-reduce's first
                                               half — same wire bytes]
  apply the optimizer to a 1/(data*fsdp) shard [mu/nu persist SHARDED —
                                               the HBM win]
  all-gather the updated params                [≈ the all-reduce's
                                               second half]

Implementation: the forward/backward stays under the implicit-SPMD jit
exactly as before (so fsdp/model/seq sharding, remat, scan, and the
Pallas seq-parallel path are untouched); only the weight update runs
inside a `shard_map` over the mesh whose in/out specs carry the joint
('data','fsdp') axis per leaf (sharding.zero_update_spec — the same
rule that lays out the persistent mu/nu, so every tree entering the
body is sliced identically and the update math is elementwise-aligned).
At the shard_map boundary the partitioner turns the pending gradient
reduction into a reduce-scatter (each device only ever needs its slice
of the summed gradient) and the exit constraint back to the params'
storage sharding compiles to the all-gather. Gradient clipping needs
the TRUE global norm, which a shard cannot measure locally — the step
computes it once outside (it already does, for the grad_norm metric)
and passes it in; the plateau/warmup schedules and `needs_loss_value`
semantics ride through unchanged because the body calls the SAME shared
optimizer-apply (train_state.gradient_update) on shards.

`parallel.grad_reduce_dtype` in {"bf16", "int8"} routes
`make_zero_train_step` to the QUANTIZED reduce-scatter
(parallel/quant.py): the forward/backward runs inside an explicit
data-parallel shard_map producing per-replica partial gradients, and
the reduction consumes quantized payloads — bf16 (stochastic
rounding) or int8 (per-chunk scale + stochastic rounding) — so the
wire really moves 2x/4x fewer bytes (verified from compiled HLO by
`collective_wire_bytes_from_hlo`, bench.py --comm). The
`zero_gradient_update` function below keeps the PR-2 cast-only bf16
behavior for the explicit seq-parallel step, whose shard_map computes
grads itself: there the cast applies to already-reduced gradients and
changes numerics only, not wire bytes (documented limitation;
docs/distributed.md).

Checkpoint compatibility: leaf SHAPES never change (only shardings), so
orbax save/restore — including the PR-1 staged overlapped save — works
with a zero-aware restore template (state_sharding(zero_update=True)),
and checkpoints remain interchangeable with the replicated mode.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from proteinbert_tpu.configs import OptimizerConfig, PretrainConfig
from proteinbert_tpu.parallel.sharding import param_spec, zero_update_spec
from proteinbert_tpu.utils.compat import shard_map

ZERO_AXES = ("data", "fsdp")

_REDUCE_DTYPES = ("fp32", "bf16")


def zero_extent(mesh: Mesh) -> int:
    """Replicas the weight update is sharded across (data x fsdp)."""
    n = 1
    for ax in ZERO_AXES:
        n *= mesh.shape.get(ax, 1)
    return n


def _update_specs(mesh: Mesh, tree: Any) -> Any:
    """Per-leaf zero specs for a params-shaped or opt-state-shaped tree
    (scalars — Adam/schedule counts, plateau state — stay replicated)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: zero_update_spec(path, leaf, mesh), tree)


def zero_gradient_update(
    mesh: Mesh,
    opt_cfg: OptimizerConfig,
    params: Any,
    grads: Any,
    opt_state: Any,
    value: Any = None,
    *,
    grad_reduce_dtype: str = "fp32",
) -> Tuple[Any, Any, jax.Array]:
    """ZeRO-1 drop-in for train_state.gradient_update, callable from
    inside any jitted step; returns (params, opt_state, grad_norm).

    The returned params are re-constrained to their ordinary storage
    sharding (param_spec) — the partitioner compiles that exit
    constraint into the all-gather — so callers build the next
    TrainState exactly as in the replicated path and repeated calls see
    stable input shardings (no retrace, donation-safe).

    grad_reduce_dtype here supports "fp32"/"bf16" only, and the bf16
    cast is NUMERICS-ONLY (it applies to already-reduced gradients —
    this entry is what the explicit seq-parallel step calls, whose own
    shard_map produced the grads). The wire-compressing bf16/int8
    reduction lives in parallel/quant.make_quant_zero_train_step,
    which make_zero_train_step routes to."""
    import optax

    from proteinbert_tpu.train.schedule import make_optimizer, needs_loss_value
    from proteinbert_tpu.train.train_state import gradient_update

    if grad_reduce_dtype not in _REDUCE_DTYPES:
        raise ValueError(
            f"unknown grad_reduce_dtype {grad_reduce_dtype!r}; "
            f"expected one of {_REDUCE_DTYPES}")

    needs_value = needs_loss_value(opt_cfg)
    # The one value a shard cannot compute locally: the clip's global
    # norm. Measured here on the full (pre-rounding) gradients — the
    # same tensor the replicated chain's clip sees.
    grad_norm = optax.global_norm(grads)
    if grad_reduce_dtype == "bf16":
        grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)

    p_specs = _update_specs(mesh, params)
    o_specs = _update_specs(mesh, opt_state)
    # Pin the gradients' layout at production: without the constraint,
    # sharding propagation inside the backward scan is free to pick an
    # interim layout (observed: the stacked-blocks LEADING axis split
    # over every device) whose reshard to the update sharding is a full
    # rematerialization. Constrained here, the pending reduction lowers
    # straight onto the update layout — the reduce-scatter.
    grads = jax.lax.with_sharding_constraint(
        grads, jax.tree.map(lambda s: NamedSharding(mesh, s), p_specs,
                            is_leaf=lambda x: isinstance(x, P)))
    # A dummy replicated scalar keeps the shard_map signature stable
    # when the schedule needs no loss value.
    value_arr = jnp.asarray(
        0.0 if value is None else value, dtype=jnp.float32)

    def body(p, g, o, g_norm, val):
        # bf16-reduced gradients re-enter optimizer precision here, on
        # the 1/(data*fsdp) shard — AFTER the wire.
        g = jax.tree.map(lambda x, ref: x.astype(ref.dtype), g, p)
        tx = make_optimizer(opt_cfg, clip_norm_value=g_norm)
        return gradient_update(tx, p, g, o, val, needs_value)

    new_params, new_opt_state = shard_map(
        body,
        mesh=mesh,
        in_specs=(p_specs, p_specs, o_specs, P(), P()),
        out_specs=(p_specs, o_specs),
        # The body mixes sharded (mu/nu/param shards) and replicated
        # (counts, plateau scalars) values; the rep/vma checker cannot
        # type the replicated outputs without psum evidence, so it is
        # off — parity with the replicated step is asserted by
        # tests/test_zero.py instead.
        check_vma=False,
    )(params, grads, opt_state, grad_norm, value_arr)

    # Exit all-gather: updated params return to their storage layout.
    store = jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_spec(path, leaf, mesh)),
        new_params)
    new_params = jax.lax.with_sharding_constraint(new_params, store)
    return new_params, new_opt_state, grad_norm


@lru_cache(maxsize=8)
def make_zero_train_step(mesh: Mesh, cfg: PretrainConfig):
    """Jitted pretraining step whose weight update is ZeRO-1-sharded —
    drop-in for train_state.train_step when cfg.parallel.zero_update
    (the trainer selects it). The front half (corruption, forward,
    loss, backward) and the plateau_value contract are SHARED code with
    the default step (train_state.corrupt_forward_grads /
    plateau_observation), not a copy — only the update differs.

    grad_reduce_dtype "bf16"/"int8" routes to the QUANTIZED
    reduce-scatter step (parallel/quant.py) — real wire-byte
    compression, same signature and plateau contract."""
    if cfg.parallel.grad_reduce_dtype != "fp32":
        from proteinbert_tpu.parallel.quant import (
            make_quant_zero_train_step,
        )

        return make_quant_zero_train_step(mesh, cfg)
    from proteinbert_tpu.train import train_state as ts
    from proteinbert_tpu.train.schedule import effective_lr

    def step(state: ts.TrainState, batch: Dict[str, jax.Array],
             plateau_value: Optional[jax.Array] = None):
        key, grads, metrics = ts.corrupt_forward_grads(state, batch, cfg)
        value = ts.plateau_observation(cfg.optimizer, metrics, plateau_value)
        params, opt_state, grad_norm = zero_gradient_update(
            mesh, cfg.optimizer, state.params, grads, state.opt_state,
            value, grad_reduce_dtype=cfg.parallel.grad_reduce_dtype,
        )

        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        metrics["lr"] = effective_lr(cfg.optimizer, opt_state, state.step)
        new_state = ts.TrainState(
            step=state.step + 1, params=params, opt_state=opt_state, key=key
        )
        return new_state, metrics

    from proteinbert_tpu.train.train_state import DONATE_STATE

    return jax.jit(step, donate_argnums=DONATE_STATE)


# ------------------------------------------------------- comm accounting

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "all-to-all", "collective-permute")


def _iter_collectives(hlo_text: str):
    """Yield (kind, output_bytes, line) for every collective op of one
    compiled per-device HLO module (shared by the output-bytes and
    wire-bytes counters below). `*-start/done` async pairs are counted
    once, at the start op, keeping only the results half of its
    (operands..., results...) tuple."""
    import re

    shape_re = re.compile(r"(\w+)\[([\d,]*)\]")
    op_re = re.compile(
        r"=\s*(.+?)\s+(" + "|".join(_COLLECTIVES) + r")(-start)?\(")
    for line in hlo_text.splitlines():
        if "-done(" in line:
            continue
        m = op_re.search(line)
        if m is None:
            continue
        shapes = [(dt, dims) for dt, dims in shape_re.findall(m.group(1))
                  if dt in _DTYPE_BYTES]
        if m.group(3) and len(shapes) >= 2 and len(shapes) % 2 == 0:
            # Async `*-start` ops return an (operands..., results...)
            # tuple — the leading half aliases the inputs; counting it
            # would double every async collective. Keep the results.
            shapes = shapes[len(shapes) // 2:]
        nbytes = 0
        for dt, dims in shapes:
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        yield m.group(2), nbytes, line


def collective_bytes_from_hlo(hlo_text: str) -> Dict[str, int]:
    """Per-collective output bytes of one compiled (per-device) HLO
    module — the recorded evidence behind the comm claims (`bench.py
    --comm`); under SPMD the module is the per-chip program, so shapes
    are per-chip shapes. The 'total' key sums every kind."""
    out: Dict[str, int] = {k: 0 for k in _COLLECTIVES}
    for kind, nbytes, _ in _iter_collectives(hlo_text):
        out[kind] += nbytes
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    return out


def _group_size(line: str, default: int) -> int:
    """Participant count of one collective op, parsed from its
    replica_groups attribute — `{{0,1,...},...}` (explicit) or
    `[G,N]<=[...]` (iota [num_groups, group_size])."""
    import re

    m = re.search(r"replica_groups=\{\{([\d,\s]*)\}", line)
    if m:
        ids = [x for x in m.group(1).split(",") if x.strip()]
        return max(len(ids), 1)
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return max(int(m.group(2)), 1)
    return default


def collective_wire_bytes_from_hlo(hlo_text: str,
                                   default_group: int = 1,
                                   ) -> Dict[str, int]:
    """Estimated per-device WIRE bytes of one compiled module's
    collectives, from the HLO itself (output shapes + replica_groups;
    never inferred from unreduced source dtypes). The output-bytes
    counter above under-represents a reduce-scatter (its per-device
    output is 1/n of what crossed the wire) and over-represents an
    all-to-all (its output already spans every peer), so quantized-vs-
    fp32 comparisons need the ring-algorithm per-device conversion:

      all-reduce      2(n-1)/n x out   (reduce-scatter + all-gather)
      reduce-scatter  (n-1)   x out    (receives n-1 foreign shards)
      all-gather      (n-1)/n x out
      all-to-all      (n-1)/n x out    (1/n of the output is local)
      collective-permute       out

    `default_group` (pass the mesh's device count) covers ops whose
    replica_groups the backend elided."""
    out: Dict[str, int] = {k: 0 for k in _COLLECTIVES}
    for kind, nbytes, line in _iter_collectives(hlo_text):
        n = _group_size(line, default_group)
        if kind == "all-reduce":
            wire = 2 * nbytes * (n - 1) // max(n, 1)
        elif kind == "reduce-scatter":
            wire = nbytes * (n - 1)
        elif kind in ("all-gather", "all-to-all"):
            wire = nbytes * (n - 1) // max(n, 1)
        else:  # collective-permute: point-to-point, output == wire
            wire = nbytes
        out[kind] += wire
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    return out


def grad_reduce_wire_bytes(wire: Dict[str, int]) -> int:
    """The gradient-REDUCTION share of a wire-bytes breakdown: the
    collectives a grad reduction can lower to (reduce-scatter under
    implicit SPMD on TPU, all-reduce on backends that fuse the slice,
    all-to-all in the explicit quantized step) — the single number the
    int8-vs-fp32 ratio gate compares (bench.py --comm,
    tools/quant_smoke.py)."""
    return (wire["reduce-scatter"] + wire["all-reduce"]
            + wire["all-to-all"])


def record_comm_metrics(registry, hlo_text: str,
                        default_group: int = 1) -> Dict[str, int]:
    """Fold one compiled module's per-collective bytes into a telemetry
    metrics registry (obs/metrics.py) as `collective_bytes{kind=...}`
    (output bytes) and `collective_wire_bytes{kind=...}` (per-device
    wire estimate) gauges — so `bench.py --comm` evidence and any
    consumer of the unified metrics stream read the SAME accounting
    instead of a private dict. Returns the collective_bytes_from_hlo
    breakdown."""
    out = collective_bytes_from_hlo(hlo_text)
    for kind, n in out.items():
        registry.gauge("collective_bytes", kind=kind).set(n)
    for kind, n in collective_wire_bytes_from_hlo(
            hlo_text, default_group).items():
        registry.gauge("collective_wire_bytes", kind=kind).set(n)
    return out


def per_chip_state_bytes(mesh: Mesh, abstract_state: Any,
                         zero_update: bool = False) -> Dict[str, int]:
    """Per-chip persistent bytes of the train state under the sharding
    rules — {'params', 'opt_state', 'total'}. Computed from shardings
    and abstract shapes alone (no allocation), so it reports the same
    number for a CPU-virtual mesh as for the real pod shape."""
    from proteinbert_tpu.parallel.sharding import state_sharding

    shardings = state_sharding(mesh, abstract_state, zero_update=zero_update)
    sizes = {"params": 0, "opt_state": 0, "other": 0}

    def add(path, leaf, sh):
        shard_shape = sh.shard_shape(leaf.shape)
        n = 1
        for d in shard_shape:
            n *= d
        nbytes = n * jnp.dtype(leaf.dtype).itemsize
        p = path[0]
        key = getattr(p, "key", None) or getattr(p, "name", None)
        sizes["params" if key == "params"
              else "opt_state" if key == "opt_state" else "other"] += nbytes

    jax.tree_util.tree_map_with_path(add, abstract_state, shardings)
    sizes["total"] = sizes["params"] + sizes["opt_state"] + sizes["other"]
    return sizes
