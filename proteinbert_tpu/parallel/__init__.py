from proteinbert_tpu.parallel.mesh import make_mesh, mesh_for_devices
from proteinbert_tpu.parallel.sharding import (
    batch_sharding, serve_batch_sharding, state_sharding,
    shard_train_state,
)
from proteinbert_tpu.parallel.halo import (
    halo_exchange, conv1d_halo, seq_parallel_conv1d,
)
from proteinbert_tpu.parallel.multihost import maybe_initialize_distributed
from proteinbert_tpu.parallel.reshard import (
    mesh_from_config, parse_mesh_spec, reshard_checkpoint, reshard_state,
    reshard_schedule_bytes, states_byte_identical,
)
from proteinbert_tpu.parallel.seq_parallel import (
    make_seq_parallel_train_step, seq_parallel_apply, sharded_global_attention,
)
from proteinbert_tpu.parallel.zero import (
    make_zero_train_step, zero_extent, zero_gradient_update,
)

__all__ = [
    "make_mesh", "mesh_for_devices",
    "batch_sharding", "serve_batch_sharding", "state_sharding",
    "shard_train_state",
    "halo_exchange", "conv1d_halo", "seq_parallel_conv1d",
    "make_seq_parallel_train_step", "seq_parallel_apply",
    "sharded_global_attention", "maybe_initialize_distributed",
    "make_zero_train_step", "zero_extent", "zero_gradient_update",
    "mesh_from_config", "parse_mesh_spec", "reshard_checkpoint",
    "reshard_state", "reshard_schedule_bytes", "states_byte_identical",
]
