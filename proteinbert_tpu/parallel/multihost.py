"""Multi-host orchestration (SURVEY §5 distributed-backend bullet).

The reference has no multi-node anything (SURVEY C18). Here multi-host is
jax-native: `jax.distributed.initialize` forms the process group (GRPC
coordination service), after which `jax.devices()` spans all hosts and
the mesh/collective machinery in this package works unchanged — each host
feeds its per-host batch shard (data/dataset.py iterators are
multi-host-lockstep by construction) and XLA runs the collectives over
ICI/DCN.

`maybe_initialize_distributed()` is the single entry point: explicit args
beat environment variables (COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID, and their SLURM equivalents via jax's own cluster detection)
beat TPU-pod auto-detection; single-host runs are a no-op. Idempotent.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

_initialized = False


def maybe_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    required: bool = False,
) -> bool:
    """Initialize the jax process group if this looks like (or is declared
    to be) a multi-host run; returns True when distributed is live.

    `required=True` (the CLI's --multihost) turns a failed init into an
    error — an operator who ASKED for multi-host must not silently get N
    independent single-host runs fighting over one checkpoint directory.
    """
    global _initialized
    if _initialized:
        return True
    already = getattr(jax.distributed, "is_initialized", lambda: False)()
    if already:
        # A launcher or earlier library call formed the group; that IS the
        # requested state, not a failure.
        _initialized = True
        return True

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.environ.get("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])

    try:
        if coordinator_address:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            # Argless: jax auto-detects TPU-pod metadata / SLURM / Open
            # MPI cluster environments; raises when there is nothing to
            # detect (single host) — which we treat as "not distributed".
            jax.distributed.initialize()
    except Exception as e:
        if required:
            raise RuntimeError(
                "multi-host initialization was requested but failed "
                f"({e}); set COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID "
                "or run on a TPU pod with metadata available") from e
        logger.info("single-host run (no distributed env detected: %s)", e)
        return False

    _initialized = True
    logger.info("jax distributed: process %d/%d, %d devices global",
                jax.process_index(), jax.process_count(), jax.device_count())
    return True
