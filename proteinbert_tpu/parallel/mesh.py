"""Device-mesh construction (SURVEY C18 — absent in the reference).

The reference is single-device PyTorch with no torch.distributed anywhere
(grep-verified, SURVEY §2 C18). This module supplies the distributed
substrate TPU-natively: a `jax.sharding.Mesh` over the ICI fabric with
four logical axes —

  data  : pure data parallelism (gradient psum)
  fsdp  : parameter/optimizer sharding over a data-like axis
          (batch is sharded over data×fsdp jointly)
  model : tensor parallelism for the G×A annotation head (SURVEY §7
          hard-part (e))
  seq   : sequence parallelism for the local conv track (XLA inserts
          conv halo exchanges; see also parallel/halo.py for the
          explicit shard_map version)

For multi-slice topologies, put 'data' outermost so the gradient
all-reduce's top level rides DCN while fsdp/model/seq collectives stay
on intra-slice ICI (scaling-book recipe).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from proteinbert_tpu.configs import MeshConfig


# Version-compat shard_map — moved to utils/compat.py (one home for the
# jax 0.4.x shims, alongside request_cpu_devices); re-exported here for
# the existing importers (seq_parallel, halo, tests).
from proteinbert_tpu.utils.compat import shard_map  # noqa: F401


def make_mesh(
    cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the (data, fsdp, model, seq) mesh from available devices.

    Uses jax.experimental.mesh_utils device ordering on real TPU slices so
    mesh-adjacent devices are ICI-adjacent; falls back to a plain reshape
    on CPU/virtual platforms.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices != n:
        raise ValueError(
            f"mesh {cfg.shape} wants {cfg.num_devices} devices, have {n}"
        )
    if devices[0].platform == "tpu":
        n_slices = len({getattr(d, "slice_index", 0) for d in devices})
        if n_slices > 1:
            # Multi-slice pod: the slower DCN hop must carry only the
            # outermost 'data' axis (its gradient psum is the one
            # collective that tolerates DCN latency — module docstring);
            # fsdp/model/seq collectives stay on intra-slice ICI.
            if cfg.data % n_slices:
                raise ValueError(
                    f"mesh data axis {cfg.data} must be a multiple of the "
                    f"{n_slices} slices so DCN carries only data "
                    "parallelism")
            from jax.experimental import mesh_utils

            per_slice = (cfg.data // n_slices, cfg.fsdp, cfg.model, cfg.seq)
            try:
                dev_array = mesh_utils.create_hybrid_device_mesh(
                    per_slice, (n_slices, 1, 1, 1), devices=devices)
                return Mesh(dev_array, cfg.axis_names)
            except Exception:  # pragma: no cover - picky topology helpers:
                # a reshape mesh is suboptimal (DCN placement not
                # guaranteed) but runs; don't crash training at startup.
                import logging

                logging.getLogger(__name__).warning(
                    "create_hybrid_device_mesh failed for %s over %d "
                    "slices; falling back to reshape ordering",
                    cfg.shape, n_slices)
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
            return Mesh(dev_array, cfg.axis_names)
        except Exception:  # pragma: no cover - topology helpers can be picky
            pass
    dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def mesh_for_devices(n: int, data: Optional[int] = None, **axes) -> Mesh:
    """Convenience: an n-device mesh, defaulting all parallelism to data."""
    cfg = MeshConfig(data=data if data is not None else n, **axes)
    return make_mesh(cfg, jax.devices()[:cfg.num_devices])
