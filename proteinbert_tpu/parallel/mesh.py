"""Device-mesh construction (SURVEY C18 — absent in the reference).

The reference is single-device PyTorch with no torch.distributed anywhere
(grep-verified, SURVEY §2 C18). This module supplies the distributed
substrate TPU-natively: a `jax.sharding.Mesh` over the ICI fabric with
four logical axes —

  data  : pure data parallelism (gradient psum)
  fsdp  : parameter/optimizer sharding over a data-like axis
          (batch is sharded over data×fsdp jointly)
  model : tensor parallelism for the G×A annotation head (SURVEY §7
          hard-part (e))
  seq   : sequence parallelism for the local conv track (XLA inserts
          conv halo exchanges; see also parallel/halo.py for the
          explicit shard_map version)

For multi-slice topologies, put 'data' outermost so the gradient
all-reduce's top level rides DCN while fsdp/model/seq collectives stay
on intra-slice ICI (scaling-book recipe).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from proteinbert_tpu.configs import MeshConfig


def make_mesh(
    cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the (data, fsdp, model, seq) mesh from available devices.

    Uses jax.experimental.mesh_utils device ordering on real TPU slices so
    mesh-adjacent devices are ICI-adjacent; falls back to a plain reshape
    on CPU/virtual platforms.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices != n:
        raise ValueError(
            f"mesh {cfg.shape} wants {cfg.num_devices} devices, have {n}"
        )
    if devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
            return Mesh(dev_array, cfg.axis_names)
        except Exception:  # pragma: no cover - topology helpers can be picky
            pass
    dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def mesh_for_devices(n: int, data: Optional[int] = None, **axes) -> Mesh:
    """Convenience: an n-device mesh, defaulting all parallelism to data."""
    cfg = MeshConfig(data=data if data is not None else n, **axes)
    return make_mesh(cfg, jax.devices()[:cfg.num_devices])
