"""Explicit sequence-parallel model path (SURVEY §5 long-context, §7 stage 10).

Under plain `jit`, XLA's SPMD partitioner already sequence-shards the
model (tests/test_parallel.py proves numerical parity) — that is the
default path. This module is the EXPLICIT shard_map version, needed when
the local track runs the Pallas fused kernel: a pallas_call is an opaque
custom call the partitioner cannot split, so the sharded program must be
written by hand. It is also the place where the communication pattern of
the architecture's context parallelism is pinned down and documented:

- local conv track: one bidirectional `ppermute` halo exchange per block
  (20 boundary residues for the k=9/d=5 wide conv) — pure neighbor ICI
  traffic, the conv analogue of ring attention's block rotation;
- global←local attention: a numerically-stable DISTRIBUTED SOFTMAX.
  Each shard computes its local scores; a `pmax` aligns the stabilizer,
  a `psum` of (exp-sum, exp·V) completes softmax(scores)·V exactly —
  per (batch, head) only a scalar + a value_dim vector cross the ICI,
  because this architecture has ONE query per head (ops/attention.py).
  This is the all-to-all-free degenerate case of ring attention: with a
  single query there is nothing to rotate, and context parallelism
  reduces to two tiny collectives per block;
- global track: replicated compute on every seq shard (G=512 is tiny);
  determinism makes the replicas bit-identical, no collective needed.

The result (for both forward and gradients — shard_map is differentiable,
and the halo/psum transpose to their adjoints automatically) matches the
unsharded model exactly; tests/test_seq_parallel.py asserts it.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from proteinbert_tpu.configs import ModelConfig, PretrainConfig
from proteinbert_tpu.data.vocab import PAD_ID
from proteinbert_tpu.kernels.fused_block import (
    fused_local_track_valid,
    local_track_valid_reference,
    pallas_supported,
    track_halo,
)
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.models.proteinbert import _cast_blocks, remat_wrap
from proteinbert_tpu.ops.layers import (
    dense_apply, embedding_apply, layer_norm_apply,
)
from proteinbert_tpu.parallel.halo import halo_exchange
from proteinbert_tpu.parallel.zero import zero_extent

Params = Dict[str, Any]

_BATCH_AXES = ("data", "fsdp")
_SEQ_AXIS = "seq"


def sharded_global_attention(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    pad_mask: jax.Array,
    axis_name: str = _SEQ_AXIS,
) -> jax.Array:
    """global_attention_apply (ops/attention.py) over a seq-sharded local
    track, via distributed softmax: exact same math as the unsharded op,
    with pmax/psum over `axis_name` supplying the global normalization."""
    dtype = local.dtype
    wq = params["wq"].astype(dtype)
    wk = params["wk"].astype(dtype)
    wv = params["wv"].astype(dtype)
    key_dim = wq.shape[-1]

    q = jnp.tanh(jnp.einsum("bg,hgk->bhk", global_, wq))
    k = jnp.tanh(jnp.einsum("blc,hck->bhlk", local, wk))
    v = jax.nn.gelu(jnp.einsum("blc,hcv->bhlv", local, wv))

    scores = jnp.einsum("bhk,bhlk->bhl", q, k) / jnp.sqrt(
        jnp.asarray(key_dim, dtype)
    )
    scores = scores.astype(jnp.float32)
    scores = jnp.where(pad_mask[:, None, :], scores, jnp.float32(-1e30))

    # Global max stabilizer: all_gather the (B, H) per-shard maxes (pmax
    # lacks a differentiation rule; the stabilizer is shift-invariant, so
    # it carries no gradient anyway).
    m = lax.stop_gradient(jnp.max(
        lax.all_gather(scores.max(axis=-1), axis_name), axis=0))  # (B, H)
    e = jnp.exp(scores - m[..., None])                      # (B, H, Ls)
    denom = lax.psum(e.sum(axis=-1), axis_name)             # (B, H)
    num = lax.psum(
        jnp.einsum("bhl,bhlv->bhv", e.astype(dtype), v), axis_name
    )                                                       # (B, H, v)
    out = num / jnp.maximum(denom[..., None], 1e-30).astype(dtype)
    b, h, vd = out.shape
    return out.reshape(b, h * vd)


def _seq_block_apply(
    params: Params,
    local: jax.Array,
    global_: jax.Array,
    pad_mask: jax.Array,
    cfg: ModelConfig,
    axis_size: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """models/proteinbert.block_apply on one seq shard (inside shard_map)."""
    track_params = {k: params[k] for k in ("narrow_conv", "wide_conv",
                                           "local_ln1", "local_dense",
                                           "local_ln2")}
    broadcast = jax.nn.gelu(dense_apply(params["global_to_local"], global_))
    H = track_halo(track_params, 1, cfg.wide_dilation)
    xh = halo_exchange(local, H, _SEQ_AXIS, axis_size)
    if cfg.use_pallas and pallas_supported(
        cfg.local_dim, local.shape[1], cfg.dtype,
        cfg.narrow_kernel, cfg.wide_kernel, cfg.wide_dilation,
    ):
        local = fused_local_track_valid(
            track_params, xh, broadcast, 1, cfg.wide_dilation, interpret
        )
    else:
        local = local_track_valid_reference(
            track_params, xh, broadcast, 1, cfg.wide_dilation
        )

    dense1 = jax.nn.gelu(dense_apply(params["global_dense1"], global_))
    attn = sharded_global_attention(params["attention"], local, global_, pad_mask)
    global_ = layer_norm_apply(params["global_ln1"], global_ + dense1 + attn)
    global_ = layer_norm_apply(
        params["global_ln2"],
        global_ + jax.nn.gelu(dense_apply(params["global_dense2"], global_)),
    )
    return local, global_


def _shard_forward(
    params: Params,
    tokens: jax.Array,
    annotations: jax.Array,
    cfg: ModelConfig,
    axis_size: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Per-shard body: mirrors proteinbert.encode + heads."""
    dtype = jnp.dtype(cfg.dtype)
    pad_mask = tokens != PAD_ID
    local = embedding_apply(params["embedding"], tokens, dtype)
    global_ = jax.nn.gelu(
        dense_apply(params["global_in"], annotations.astype(dtype))
    )

    body = remat_wrap(
        partial(_seq_block_apply, cfg=cfg, axis_size=axis_size,
                interpret=interpret),
        cfg,
    )

    if cfg.scan_blocks:
        def scan_body(carry, blk):
            l, g = carry
            l, g = body(blk, l, g, pad_mask)
            return (l, g), None

        # Same hoist as proteinbert.encode: cast the block stack to the
        # compute dtype ONCE outside the scan, so the f32->bf16 convert
        # is not re-run per block (and per backward recompute) inside
        # the remat-wrapped body.
        (local, global_), _ = lax.scan(
            scan_body, (local, global_),
            _cast_blocks(params["blocks"], dtype),
            unroll=cfg.scan_unroll,
            _split_transpose=cfg.scan_split_transpose)
    else:
        for blk in params["blocks"]:
            local, global_ = body(blk, local, global_, pad_mask)

    local_logits = dense_apply(params["local_head"], local).astype(jnp.float32)
    global_logits = dense_apply(params["global_head"], global_).astype(jnp.float32)
    return local_logits, global_logits


def seq_parallel_apply(
    mesh: Mesh,
    params: Params,
    tokens: jax.Array,
    annotations: jax.Array,
    cfg: ModelConfig,
) -> Tuple[jax.Array, jax.Array]:
    """Forward pass with the sequence axis explicitly sharded over the
    mesh's 'seq' axis (batch over data×fsdp). Interface and results match
    models/proteinbert.apply; use when cfg.use_pallas needs to run under
    sequence parallelism (see module docstring)."""
    axis_size = mesh.shape[_SEQ_AXIS]
    interpret = jax.default_backend() != "tpu"
    fn = partial(_shard_forward, cfg=cfg, axis_size=axis_size,
                 interpret=interpret)
    from proteinbert_tpu.parallel.mesh import shard_map

    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(), P(_BATCH_AXES, _SEQ_AXIS), P(_BATCH_AXES, None)),
        out_specs=(P(_BATCH_AXES, _SEQ_AXIS, None), P(_BATCH_AXES, None)),
        # pallas_call's out_shape carries no varying-mesh-axes metadata,
        # so the vma/rep checker cannot type the fused-kernel path.
        check_vma=False,
    )(params, tokens, annotations)


@lru_cache(maxsize=8)
def make_seq_parallel_train_step(mesh: Mesh, cfg: PretrainConfig):
    """Jitted pretraining step whose forward runs seq_parallel_apply —
    drop-in for train_state.train_step when (seq > 1 and use_pallas).
    Corruption, loss, optimizer update are shared with the default step.

    grad_reduce_dtype="int8" is REJECTED here (typed QuantConfigError,
    mirroring the packing rejection below): the quantized reduce-
    scatter (parallel/quant.py) needs per-replica partial gradients
    from its own data-parallel shard_map, and this step's hand-written
    seq shard_map already owns the gradient computation — its grads
    exit as fully-reduced logical tensors the quantizer cannot
    compress. "bf16" stays the PR-2 cast-only reduction here
    (numerics, not wire — docs/distributed.md)."""
    if cfg.parallel.zero_update and cfg.parallel.grad_reduce_dtype == "int8":
        from proteinbert_tpu.parallel.quant import QuantConfigError

        raise QuantConfigError(
            "grad_reduce_dtype='int8' is not supported by the explicit "
            "sequence-parallel Pallas step: the quantized reduce-"
            "scatter needs per-replica partial gradients from its own "
            "data-parallel shard_map, which this hand-sharded path "
            "cannot provide. Disable model.use_pallas (the implicit-"
            "SPMD jit cannot quantize either — use a data/fsdp mesh), "
            "or keep grad_reduce_dtype to 'fp32'/'bf16' here.")
    import optax

    from proteinbert_tpu.data.corruption import corrupt_batch
    from proteinbert_tpu.train import train_state as ts
    from proteinbert_tpu.train.loss import pretrain_loss
    from proteinbert_tpu.train.schedule import make_optimizer, needs_loss_value

    def step(state, batch):
        if "segment_ids" in batch:
            raise NotImplementedError(
                "packed batches (data.packing) are not supported by the "
                "explicit sequence-parallel Pallas step: the fused kernel "
                "has no segment-boundary support yet (its guard falls "
                "back to XLA, which this hand-sharded path cannot use). "
                "Disable model.use_pallas (the implicit-SPMD jit "
                "seq-shards the boundary-masked packed model fine) or "
                "turn packing off.")
        key, step_key = jax.random.split(state.key)
        X, Y, W = corrupt_batch(
            step_key, batch["tokens"], batch["annotations"],
            token_randomize_prob=cfg.data.token_randomize_prob,
            annotation_corrupt_prob=cfg.data.annotation_corrupt_prob,
            annotation_drop_prob=cfg.data.annotation_drop_prob,
            annotation_add_prob=cfg.data.annotation_add_prob,
        )

        def loss_fn(params):
            local_logits, global_logits = seq_parallel_apply(
                mesh, params, X["local"], X["global"], cfg.model
            )
            return pretrain_loss(local_logits, global_logits, Y, W)

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        if cfg.parallel.zero_update and zero_extent(mesh) > 1:
            # ZeRO-1 weight update (parallel/zero.py): same shared
            # optimizer-apply, run on 1/(data*fsdp) shards between a
            # gradient reduce-scatter and a param all-gather.
            from proteinbert_tpu.parallel.zero import zero_gradient_update

            params, opt_state, grad_norm = zero_gradient_update(
                mesh, cfg.optimizer, state.params, grads, state.opt_state,
                metrics["loss"],
                grad_reduce_dtype=cfg.parallel.grad_reduce_dtype,
            )
        else:
            params, opt_state = ts.gradient_update(
                make_optimizer(cfg.optimizer), state.params, grads,
                state.opt_state, metrics["loss"],
                needs_loss_value(cfg.optimizer),
            )
            grad_norm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        from proteinbert_tpu.train.schedule import effective_lr

        metrics["lr"] = effective_lr(cfg.optimizer, opt_state, state.step)
        return ts.TrainState(step=state.step + 1, params=params,
                             opt_state=opt_state, key=key), metrics

    return jax.jit(step, donate_argnums=ts.DONATE_STATE)
