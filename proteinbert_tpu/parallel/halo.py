"""Explicit sequence-parallel Conv1d via shard_map + ppermute halo exchange.

Long-context story (SURVEY §5): the local conv track is sharded over the
'seq' mesh axis; each shard needs `(k-1)/2 · dilation` boundary residues
from its neighbors (20 for the wide k=9 d=5 conv). Under plain `jit` XLA's
SPMD partitioner inserts this halo exchange automatically — that is the
default path (ops/layers.py). This module is the EXPLICIT version, for
(a) the Pallas kernel path, where the conv body is opaque to the SPMD
partitioner and the exchange must be done by hand, and (b) pinning the
communication pattern (one bidirectional ppermute per conv, pure ICI
neighbor traffic — the conv-track analogue of ring attention).

Edge shards receive zeros, matching 'SAME' zero padding.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from proteinbert_tpu.ops.layers import Params


def halo_exchange(
    x: jax.Array, halo: int, axis_name: str, axis_size: int
) -> jax.Array:
    """Pad the (B, L_shard, C) local block with `halo` rows from each
    side's neighbors along `axis_name` (zeros at the mesh edges).

    Handles halo > L_shard (e.g. the wide dilated conv on small test
    shards) by hopping multiple neighbors: each round forwards the block
    received in the previous round, so round r delivers shard i∓r's rows.
    Real configs need one round (L=2048/seq=4 → 512-row shards vs halo 20).
    """
    if halo == 0:
        return x
    if axis_size == 1:
        pad = jnp.zeros(x.shape[:1] + (halo,) + x.shape[2:], x.dtype)
        return jnp.concatenate([pad, x, pad], axis=1)
    L = x.shape[1]
    rounds = min(-(-halo // L), axis_size - 1)
    right_perm = [(i, i + 1) for i in range(axis_size - 1)]
    left_perm = [(i + 1, i) for i in range(axis_size - 1)]

    # Left context: blocks of shards i-1, i-2, ... (nearest last).
    left_blocks, cur = [], x
    for _ in range(rounds):
        cur = lax.ppermute(cur, axis_name, perm=right_perm)  # shard 0 gets zeros
        left_blocks.insert(0, cur)
    left = jnp.concatenate(left_blocks, axis=1)[:, -halo:, :] if rounds * L >= halo \
        else jnp.concatenate(
            [jnp.zeros(x.shape[:1] + (halo - rounds * L,) + x.shape[2:], x.dtype)]
            + left_blocks, axis=1)

    # Right context: blocks of shards i+1, i+2, ... (nearest first).
    right_blocks, cur = [], x
    for _ in range(rounds):
        cur = lax.ppermute(cur, axis_name, perm=left_perm)  # last shard gets zeros
        right_blocks.append(cur)
    right = jnp.concatenate(right_blocks, axis=1)[:, :halo, :] if rounds * L >= halo \
        else jnp.concatenate(
            right_blocks
            + [jnp.zeros(x.shape[:1] + (halo - rounds * L,) + x.shape[2:], x.dtype)],
            axis=1)

    return jnp.concatenate([left, x, right], axis=1)


def conv1d_halo(
    params: Params,
    x: jax.Array,
    dilation: int,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """'SAME' Conv1d on a seq-sharded (B, L_shard, C) block, inside
    shard_map: halo-exchange then VALID conv. Requires odd kernel."""
    kernel = params["kernel"]
    k = kernel.shape[0]
    assert k % 2 == 1, "halo conv requires odd kernel"
    halo = (k - 1) // 2 * dilation
    xh = halo_exchange(x, halo, axis_name, axis_size)
    y = lax.conv_general_dilated(
        xh,
        kernel.astype(x.dtype),
        window_strides=(1,),
        padding="VALID",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    return y + params["bias"].astype(x.dtype)


def seq_parallel_conv1d(
    mesh: Mesh, params: Params, x: jax.Array, dilation: int = 1
) -> jax.Array:
    """Standalone sharded 'SAME' conv over a global (B, L, C) array whose
    L axis is (to be) sharded over mesh axis 'seq' and B over data×fsdp."""
    n_seq = mesh.shape["seq"]

    fn = partial(
        conv1d_halo, dilation=dilation, axis_name="seq", axis_size=n_seq
    )
    from proteinbert_tpu.parallel.mesh import shard_map

    return shard_map(
        lambda p, xb: fn(p, xb),
        mesh=mesh,
        in_specs=(P(), P(("data", "fsdp"), "seq", None)),
        out_specs=P(("data", "fsdp"), "seq", None),
    )(params, x)
