"""Pretrain→fine-tune TRANSFER experiment through the real CLI.

VERDICT r2 Missing #3 / item 4: fine-tuning converged standalone, but no
experiment showed a pretrained trunk beating a random-init trunk — the
entire point of ProteinBERT's pretraining (the reference's fine-tune
ambition is commented-out code, reference utils.py:348-493).

Protocol (every phase is a REAL CLI subprocess, not an in-process call):
  1. Generate a STRUCTURED corpus (data/synthetic.make_structured_proteins:
     two-state Markov sequences + 3-mer annotations) and write it in the
     etl/h5_builder HDF5 layout.
  2. `pretrain --data corpus.h5` for --steps steps → run dir.
  3. Few-shot downstream tasks from HELD-OUT structured proteins:
     - per-residue `token_classification`: recover the hidden state
       (the secondary-structure miniature), --train-rows labeled rows;
     - per-protein `sequence_regression`: the hidden state-1 fraction.
  4. `finetune` each task twice — `--pretrained <run>` vs random init —
     on identical data/epochs/seeds (trunk frozen, so the comparison is
     exactly "pretrained features vs random features").
  5. Print ONE JSON line: per-task pretrained/random best eval scores
     and the gaps.

Scales: --scale mini (CPU, ~15 min on one core — the smoke of this
harness), --scale small (CPU fallback when the TPU tunnel is down;
sized for a multi-core host — measured ~113 s/step ≈ 30+ h on a
SINGLE-core box, so check `nproc` before choosing it; defaults
--platform cpu like mini), or --scale full (the recorded run;
TPU-sized model/steps).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCALES = {
    # model/trunk geometry, pretrain steps, corpus rows, few-shot rows
    # Fine-tunes are frozen-trunk linear probes on ~tens of labeled rows:
    # the head needs a few hundred updates and tolerates a high LR (both
    # arms get identical settings, so the comparison stays fair).
    "mini": dict(local_dim=64, global_dim=128, key_dim=16, num_heads=4,
                 num_blocks=2, seq_len=128, batch=16, steps=400,
                 corpus=1024, train_rows=32, eval_rows=128, epochs=40,
                 head_lr=3e-3),
    # CPU-runnable in ~1 h — the recorded fallback when the TPU tunnel
    # is down for the whole session.
    "small": dict(local_dim=128, global_dim=256, key_dim=32, num_heads=4,
                  num_blocks=3, seq_len=256, batch=32, steps=1000,
                  corpus=4096, train_rows=48, eval_rows=256, epochs=40,
                  head_lr=3e-3),
    "full": dict(local_dim=256, global_dim=512, key_dim=64, num_heads=8,
                 num_blocks=4, seq_len=512, batch=64, steps=4000,
                 corpus=16384, train_rows=64, eval_rows=512, epochs=40,
                 head_lr=3e-3),
}


def write_corpus_h5(path, seqs, ann):
    """The etl/h5_builder dataset layout (names per reference
    uniref_dataset.py:238-245), written directly for the synthetic
    corpus."""
    import h5py

    with h5py.File(path, "w") as f:
        sd = h5py.string_dtype()
        f.create_dataset("seqs", data=np.array(seqs, dtype=object), dtype=sd)
        f.create_dataset("uniprot_ids",
                         data=np.array([f"SYN{i}" for i in range(len(seqs))],
                                       dtype=object), dtype=sd)
        f.create_dataset("seq_lengths",
                         data=np.array([len(s) for s in seqs], np.int32))
        f.create_dataset("annotation_masks", data=ann.astype(bool))
        f.create_dataset("included_annotations",
                         data=np.array([f"GO:{i:07d}"
                                        for i in range(ann.shape[1])],
                                       dtype=object), dtype=sd)


def write_task_tsvs(outdir, seqs, states, train_rows, eval_rows):
    """token-classification (per-residue hidden state) and regression
    (state-1 fraction) TSVs in the data/finetune_data.py format."""
    paths = {}
    splits = {"train": slice(0, train_rows),
              "eval": slice(train_rows, train_rows + eval_rows)}
    for split, sl in splits.items():
        tok = os.path.join(outdir, f"state_{split}.tsv")
        with open(tok, "w") as f:
            for s, st in zip(seqs[sl], states[sl]):
                f.write(f"{s}\t{''.join(str(int(x)) for x in st)}\n")
        paths[f"token_{split}"] = tok
        reg = os.path.join(outdir, f"frac_{split}.tsv")
        with open(reg, "w") as f:
            for s, st in zip(seqs[sl], states[sl]):
                f.write(f"{s}\t{float(np.mean(st)):.6f}\n")
        paths[f"reg_{split}"] = reg
    return paths


def run_cli(args_list, platform=None, env=None):
    pre = ["--platform", platform] if platform else []
    cmd = [sys.executable, "-m", "proteinbert_tpu"] + pre + args_list
    print("+ " + " ".join(pre + args_list), file=sys.stderr, flush=True)
    # Bounded per phase on tunnel-exposed platforms only: a mid-phase
    # tunnel drop hangs the CLI child at device init/compile forever.
    # CPU phases (where no such hang exists) stay unbounded — a slow
    # but progressing full-scale CPU run must not be misdiagnosed as a
    # drop. Two layers: subprocess.run's timeout kills the child while
    # THIS process lives, and PBT_SELF_DESTRUCT_SECS arms a SIGALRM in
    # the child (cli/main.py) so an outer kill of this harness cannot
    # orphan a hung child still holding the single chip's client.
    # The default bound assumes a tunnel-exposed device platform; a
    # slow-but-healthy non-tunnel host (ADVICE r3) should set
    # PBT_TX_PHASE_TIMEOUT=0 (unbounded) or higher explicitly.
    phase_timeout = int(os.environ.get(
        "PBT_TX_PHASE_TIMEOUT", 0 if platform == "cpu" else 3600))
    run_env = dict(env or os.environ)
    if phase_timeout > 0:
        run_env.setdefault("PBT_SELF_DESTRUCT_SECS",
                           str(phase_timeout + 60))
    try:
        r = subprocess.run(cmd, cwd=REPO, env=run_env,
                           timeout=phase_timeout or None)
    except subprocess.TimeoutExpired:
        raise SystemExit(
            f"CLI phase exceeded {phase_timeout}s — a tunnel drop hangs "
            "device init/compile forever, but if this host is merely slow "
            "(no tunnel), rerun with PBT_TX_PHASE_TIMEOUT=0 (unbounded) "
            f"or a larger bound: {' '.join(cmd)}")
    if r.returncode != 0:
        raise SystemExit(f"CLI failed ({r.returncode}): {' '.join(cmd)}")


def best_score(history_json):
    with open(history_json) as f:
        hist = json.load(f)
    evals = [h for h in hist if any(k.startswith("eval_") for k in h)]
    if not evals:
        raise SystemExit(f"no eval records in {history_json}")
    if any("eval_accuracy" in h for h in evals):
        return max(h["eval_accuracy"] for h in evals if "eval_accuracy" in h)
    return -min(h["eval_loss"] for h in evals if "eval_loss" in h)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=SCALES, default="mini")
    ap.add_argument("--outdir", default=os.path.join(REPO, "transfer_run"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, help="override pretrain steps")
    ap.add_argument("--platform", choices=("cpu", "tpu", "axon"),
                    help="forwarded to every CLI call; defaults to cpu "
                         "for the CPU-sized scales (a dead TPU tunnel "
                         "otherwise hangs the subprocesses at device "
                         "init)")
    args = ap.parse_args()
    platform = args.platform or ("cpu" if args.scale != "full" else None)
    S = dict(SCALES[args.scale])
    if args.steps:
        S["steps"] = args.steps
    os.makedirs(args.outdir, exist_ok=True)

    from proteinbert_tpu.data.synthetic import make_structured_proteins

    rng = np.random.default_rng(args.seed)
    n_task = S["train_rows"] + S["eval_rows"]
    seqs, ann, states = make_structured_proteins(
        S["corpus"] + n_task, rng, num_annotations=256,
        max_len=min(250, S["seq_len"] - 2))
    corpus_h5 = os.path.join(args.outdir, "corpus.h5")
    write_corpus_h5(corpus_h5, seqs[:S["corpus"]], ann[:S["corpus"]])
    # Task rows are DISJOINT from the pretrain corpus.
    paths = write_task_tsvs(args.outdir, seqs[S["corpus"]:],
                            states[S["corpus"]:],
                            S["train_rows"], S["eval_rows"])

    model_set = [f"--set=model.{k}={S[k]}" for k in
                 ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks")]
    run_dir = os.path.join(args.outdir, "pretrain_run")
    run_cli(["pretrain", "--preset", "tiny", "--data", corpus_h5,
             "--eval-frac", "0.05",
             "--checkpoint-dir", run_dir,
             "--history-json", os.path.join(args.outdir, "pretrain_hist.json"),
             *model_set,
             f"--set=data.seq_len={S['seq_len']}",
             f"--set=data.batch_size={S['batch']}",
             f"--set=train.max_steps={S['steps']}",
             "--set=train.log_every=50",
             f"--set=train.eval_every={max(S['steps'] // 8, 50)}",
             f"--set=checkpoint.every_steps={max(S['steps'] // 4, 100)}",
             f"--set=optimizer.warmup_steps={max(S['steps'] // 10, 20)}"],
            platform=platform)

    results = {}
    for task, num_out, train_key, eval_key in (
        ("token_classification", 2, "token_train", "token_eval"),
        ("sequence_regression", 1, "reg_train", "reg_eval"),
    ):
        scores = {}
        for arm in ("pretrained", "random"):
            hist = os.path.join(args.outdir, f"{task}_{arm}_hist.json")
            ck = os.path.join(args.outdir, f"{task}_{arm}_ck")
            cli = ["finetune", "--preset", "tiny", "--task", task,
                   "--num-outputs", str(num_out),
                   "--epochs", str(S["epochs"]), "--freeze-trunk",
                   "--data", paths[train_key], "--eval-data", paths[eval_key],
                   "--checkpoint-dir", ck, "--history-json", hist,
                   *model_set,
                   f"--set=data.seq_len={S['seq_len']}",
                   "--set=data.batch_size=8",
                   f"--set=optimizer.learning_rate={S['head_lr']}",
                   "--set=optimizer.warmup_steps=10"]
            if arm == "pretrained":
                cli += ["--pretrained", run_dir]
            run_cli(cli, platform=platform)
            scores[arm] = best_score(hist)
        results[task] = {**scores,
                         "gap": scores["pretrained"] - scores["random"]}

    line = {"scale": args.scale, "steps": S["steps"],
            "train_rows": S["train_rows"], **results}
    print(json.dumps(line))
    with open(os.path.join(args.outdir, "transfer_result.json"), "w") as f:
        json.dump(line, f, indent=2)


if __name__ == "__main__":
    main()
