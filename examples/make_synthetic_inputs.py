"""Generate a synthetic miniature of the real inputs for the example
workflow: a GO OBO file, a UniRef90-shaped XML, the matching FASTA, and
fine-tuning TSVs. Shapes mirror the real artifacts (reference
uniref_dataset.py:76-98 element layout, go.txt OBO format) at ~1/10^6
scale so the whole pipeline runs in seconds on a laptop or one chip.

Usage: python examples/make_synthetic_inputs.py <out_dir>
"""

import gzip
import os
import sys

import numpy as np

AA = "ACDEFGHIKLMNPQRSTVWY"
N_GO = 24            # GO terms in a 3-level DAG
N_PROTEINS = 120
CATEGORIES = ["GO Molecular Function", "GO Biological Process",
              "GO Cellular Component"]


def go_obo() -> str:
    """3-level DAG: term 1 is the root; 2..8 are its children; the rest
    hang off those."""
    blocks = []
    for i in range(1, N_GO + 1):
        lines = [f"[Term]", f"id: GO:{i:07d}", f"name: term{i}",
                 "namespace: molecular_function"]
        if 2 <= i <= 8:
            lines.append("is_a: GO:0000001 ! term1")
        elif i > 8:
            parent = 2 + (i % 7)
            lines.append(f"is_a: GO:{parent:07d} ! term{parent}")
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks) + "\n"


def main(out_dir: str) -> None:
    rng = np.random.default_rng(0)
    os.makedirs(out_dir, exist_ok=True)

    with open(os.path.join(out_dir, "go.txt"), "w") as f:
        f.write(go_obo())

    entries, fasta = [], []
    tsv_rows = []
    for p in range(N_PROTEINS):
        acc = f"P{p:05d}"
        seq = "".join(rng.choice(list(AA), size=rng.integers(20, 120)))
        fasta.append(f">UniRef90_{acc} cluster member\n{seq}\n")
        # each protein gets 1-4 random leaf GO terms in random categories
        props = "\n".join(
            f'        <property type="{rng.choice(CATEGORIES)}" '
            f'value="GO:{int(g):07d}"/>'
            for g in rng.choice(np.arange(9, N_GO + 1),
                                size=rng.integers(1, 5), replace=False)
        )
        entries.append(f"""\
  <entry id="UniRef90_{acc}" updated="2024-01-01">
    <name>Cluster: protein {acc}</name>
    <representativeMember>
      <dbReference type="UniProtKB ID" id="{acc}_SYNTH">
        <property type="NCBI taxonomy" value="{int(rng.integers(1, 99999))}"/>
{props}
      </dbReference>
      <sequence length="{len(seq)}">IGNORED</sequence>
    </representativeMember>
  </entry>
""")
        # fine-tune task: per-protein label = is the sequence K-rich?
        tsv_rows.append(f"{seq}\t{int(seq.count('K') > len(seq) * 0.05)}")

    with gzip.open(os.path.join(out_dir, "uniref90.xml.gz"), "wt") as f:
        f.write('<?xml version="1.0" encoding="ISO-8859-1"?>\n'
                '<UniRef90 xmlns="http://uniprot.org/uniref" '
                'releaseDate="2024-01-01">\n' + "".join(entries)
                + "</UniRef90>\n")
    with open(os.path.join(out_dir, "uniref90.fasta"), "w") as f:
        f.write("".join(fasta))
    split = int(N_PROTEINS * 0.8)
    with open(os.path.join(out_dir, "train.tsv"), "w") as f:
        f.write("\n".join(tsv_rows[:split]) + "\n")
    with open(os.path.join(out_dir, "dev.tsv"), "w") as f:
        f.write("\n".join(tsv_rows[split:]) + "\n")
    print(f"wrote go.txt, uniref90.xml.gz, uniref90.fasta, "
          f"train.tsv, dev.tsv to {out_dir}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "example_inputs")
