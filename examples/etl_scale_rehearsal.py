"""ETL scale rehearsal: time + memory-profile the full offline pipeline
(XML.gz → SQLite → HDF5) on a ~100k-entry synthetic UniRef90 miniature.

The reference's parse is an hours-scale job on the real corpus (SURVEY
§3.2: `uniref_dataset.py:374-393` hot loop) but was only ever exercised at
toy size here in round 1 (VERDICT r1 Weak #5). This script generates a
realistically-shaped corpus of N entries STREAMING to disk (constant
memory), then runs each ETL stage under wall-clock + peak-RSS
measurement and prints one JSON summary with entries/sec per stage and an
extrapolation to UniRef90 scale (~1.5e8 clusters). Run it after ETL
changes; BASELINE.md records the reference numbers.

Usage: python examples/etl_scale_rehearsal.py [n_entries] [out_dir]
Defaults: 100_000 entries into a temp dir (deleted on success).
"""

import gzip
import json
import os
import resource
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

AA = "ACDEFGHIKLMNPQRSTVWY"
N_GO = 600          # 3-level DAG, ~real go.txt order of magnitude is 47k;
                    # 600 keeps annotation vectors realistic per protein
CATEGORIES = ["GO Molecular Function", "GO Biological Process",
              "GO Cellular Component"]
UNIREF90_SCALE = 1.5e8  # clusters in a modern UniRef90 release


def _peak_rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def write_go_obo(path: str) -> None:
    with open(path, "w") as f:
        for i in range(1, N_GO + 1):
            f.write(f"[Term]\nid: GO:{i:07d}\nname: term{i}\n"
                    "namespace: molecular_function\n")
            if 2 <= i <= 40:
                f.write("is_a: GO:0000001 ! term1\n")
            elif i > 40:
                parent = 2 + (i % 39)
                f.write(f"is_a: GO:{parent:07d} ! term{parent}\n")
            f.write("\n")


def write_corpus(xml_path: str, fasta_path: str, n: int, seed: int = 0) -> None:
    """Stream n synthetic entries (UniRef90 element layout per reference
    uniref_dataset.py:76-98; FASTA 60-col wrapped) without holding the
    corpus in memory."""
    rng = np.random.default_rng(seed)
    aa = np.array(list(AA))
    with gzip.open(xml_path, "wt", compresslevel=1) as xf, \
            open(fasta_path, "w") as ff:
        xf.write('<?xml version="1.0" encoding="ISO-8859-1"?>\n'
                 '<UniRef90 xmlns="http://uniprot.org/uniref" '
                 'releaseDate="2026-01-01">\n')
        for p in range(n):
            acc = f"P{p:07d}"
            # Real UniRef90 length distribution is ~lognormal, median ~250.
            length = int(np.clip(rng.lognormal(5.5, 0.6), 30, 2000))
            seq = "".join(rng.choice(aa, size=length))
            ff.write(f">UniRef90_{acc} cluster member\n")
            for j in range(0, length, 60):
                ff.write(seq[j:j + 60] + "\n")
            n_go = int(rng.integers(0, 8))
            props = "".join(
                f'        <property type="{CATEGORIES[int(g) % 3]}" '
                f'value="GO:{int(g):07d}"/>\n'
                for g in rng.integers(41, N_GO + 1, size=n_go)
            )
            xf.write(
                f'  <entry id="UniRef90_{acc}" updated="2026-01-01">\n'
                f'    <name>Cluster: protein {acc}</name>\n'
                f'    <representativeMember>\n'
                f'      <dbReference type="UniProtKB ID" id="{acc}_SYNTH">\n'
                f'        <property type="NCBI taxonomy" '
                f'value="{int(rng.integers(1, 99999))}"/>\n'
                f'{props}'
                f'      </dbReference>\n'
                f'      <sequence length="{length}">IGNORED</sequence>\n'
                f'    </representativeMember>\n'
                f'  </entry>\n')
        xf.write("</UniRef90>\n")


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    keep = len(sys.argv) > 2
    out_dir = sys.argv[2] if keep else tempfile.mkdtemp(prefix="etl_rehearsal_")
    os.makedirs(out_dir, exist_ok=True)
    # Printed up front so a mid-stage crash leaves a findable artifact dir
    # (kept deliberately on any failure — only a clean run deletes it).
    print(f"rehearsal dir: {out_dir}", file=sys.stderr)

    from proteinbert_tpu.etl import (
        UnirefToSqliteParser, create_h5_dataset, parse_obo, save_meta_csv,
    )
    from proteinbert_tpu.etl.fasta import build_index

    paths = {k: os.path.join(out_dir, v) for k, v in {
        "go": "go.txt", "xml": "uniref90.xml.gz", "fasta": "uniref90.fasta",
        "db": "uniref.db", "meta": "go_meta.csv", "h5": "dataset.h5",
    }.items()}

    stages = {}

    def stage(name, fn):
        t0, rss0 = time.perf_counter(), _peak_rss_mb()
        fn()
        dt = time.perf_counter() - t0
        stages[name] = {"seconds": round(dt, 2),
                        "entries_per_sec": round(n / dt, 1),
                        "peak_rss_mb": round(_peak_rss_mb(), 1)}
        print(f"[{name}] {dt:.1f}s  {n / dt:,.0f} entries/s  "
              f"peak RSS {_peak_rss_mb():.0f} MB (was {rss0:.0f})",
              file=sys.stderr)

    write_go_obo(paths["go"])
    stage("generate", lambda: write_corpus(paths["xml"], paths["fasta"], n))

    onto = parse_obo(paths["go"])

    def run_parse():
        parser = UnirefToSqliteParser(paths["xml"], onto, paths["db"],
                                      verbose=False)
        parser.parse()
        save_meta_csv(onto, paths["meta"], counts=parser.go_record_counts,
                      total_records=parser.n_records_with_any_go)

    stage("xml_to_sqlite", run_parse)
    stage("fasta_index", lambda: build_index(paths["fasta"]))

    rows = []
    stage("h5_build", lambda: rows.append(create_h5_dataset(
        paths["db"], paths["fasta"], paths["meta"], paths["h5"],
        min_records_to_keep_annotation=100, verbose=False)))

    pipeline_s = (stages["xml_to_sqlite"]["seconds"]
                  + stages["fasta_index"]["seconds"]
                  + stages["h5_build"]["seconds"])
    summary = {
        "n_entries": n,
        "rows_in_h5": rows[0],
        "stages": stages,
        "pipeline_seconds": round(pipeline_s, 1),
        "pipeline_entries_per_sec": round(n / pipeline_s, 1),
        "uniref90_extrapolation_hours": round(
            UNIREF90_SCALE / (n / pipeline_s) / 3600.0, 1),
        "peak_rss_mb": round(_peak_rss_mb(), 1),
    }
    print(json.dumps(summary))
    # Assert BEFORE cleanup: a failing rehearsal must leave its
    # db/h5/fasta behind for debugging (the temp dir path is printed).
    assert rows[0] > 0.9 * n, (
        f"join lost too many rows: {rows[0]}/{n}; artifacts kept in {out_dir}")
    if not keep:
        shutil.rmtree(out_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
