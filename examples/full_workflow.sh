#!/usr/bin/env bash
# End-to-end workflow on synthetic miniature data: offline ETL → denoising
# pretrain → fine-tune → evaluate → inference → weight export. Every stage
# is the same CLI a real UniRef90 run uses; only the inputs are synthetic.
# Runs in a few minutes on CPU or one TPU chip from the repo root:
#   bash examples/full_workflow.sh [workdir]
# Force a backend (e.g. when the TPU is unreachable):
#   PB_PLATFORM=cpu bash examples/full_workflow.sh
set -euo pipefail

W="${1:-$(mktemp -d /tmp/pb_workflow.XXXX)}"
echo "=== workdir: $W"

PB=(python -m proteinbert_tpu)
[ -n "${PB_PLATFORM:-}" ] && PB+=(--platform "$PB_PLATFORM")

# Tiny model overrides shared by every stage that builds the model.
TINY=(--set model.num_blocks=2 --set model.local_dim=32
      --set model.global_dim=64 --set model.key_dim=16
      --set data.seq_len=128 --set data.batch_size=8)

echo "=== 0. synthetic inputs (GO OBO + UniRef XML + FASTA + task TSVs)"
python examples/make_synthetic_inputs.py "$W/inputs"

echo "=== 1. offline ETL: XML -> SQLite"
"${PB[@]}" create-uniref-db \
    --uniref-xml "$W/inputs/uniref90.xml.gz" \
    --go-meta "$W/inputs/go.txt" \
    --output-db "$W/ann.db" --go-meta-csv "$W/meta.csv"

echo "=== 2. offline ETL: SQLite + FASTA -> HDF5"
"${PB[@]}" create-h5 \
    --db "$W/ann.db" --fasta "$W/inputs/uniref90.fasta" \
    --go-meta-csv "$W/meta.csv" --output "$W/data.h5" \
    --min-records 2   # the real-data default of 100 needs ~1M records

echo "=== 3. denoising pretrain on the HDF5 (held-out eval fraction)"
"${PB[@]}" pretrain --preset tiny --data "$W/data.h5" \
    --max-steps 120 --eval-frac 0.1 \
    --checkpoint-dir "$W/pretrain" --history-json "$W/pretrain_history.json" \
    "${TINY[@]}" \
    --set train.log_every=40 --set train.eval_every=60 \
    --set optimizer.warmup_steps=20 --set checkpoint.every_steps=60

echo "=== 4. standalone evaluation of the checkpoint"
"${PB[@]}" evaluate --pretrained "$W/pretrain" \
    --data "$W/data.h5" --max-batches 4

echo "=== 5. fine-tune a per-protein classification head on the trunk"
"${PB[@]}" finetune --task sequence_classification \
    --num-outputs 2 --epochs 3 --pretrained "$W/pretrain" \
    --data "$W/inputs/train.tsv" --eval-data "$W/inputs/dev.tsv" \
    --checkpoint-dir "$W/finetune" --history-json "$W/finetune_history.json"

echo "=== 6. inference: embeddings, GO prediction, masked-residue filling"
"${PB[@]}" embed --pretrained "$W/pretrain" \
    --fasta "$W/inputs/uniref90.fasta" --output "$W/embeddings.h5"
"${PB[@]}" predict-go --pretrained "$W/pretrain" \
    --go-meta-csv "$W/meta.csv" --data "$W/data.h5" --top-k 3 \
    MKVLAAGIAKWTACDEFGHIK
"${PB[@]}" predict-residues --pretrained "$W/pretrain" \
    "MKV?AAGIAK?T"

echo "=== 7. portability: flat NPZ export / import round trip"
"${PB[@]}" export-weights --pretrained "$W/pretrain" \
    --output "$W/weights.npz"
# import-weights needs the weights' exact geometry; the pretrain run
# recorded its resolved config (incl. the annotation count adopted from
# the HDF5) in config.json, so read the one data-dependent field there.
NA=$(python -c "import json; print(json.load(open('$W/pretrain/config.json'))['model']['num_annotations'])")
"${PB[@]}" import-weights --weights "$W/weights.npz" \
    --output "$W/imported" --preset tiny "${TINY[@]}" \
    --set "model.num_annotations=$NA"

echo "=== done — artifacts in $W"
